"""Parity: the batched TPU engine vs the pure-Python oracle (M2 plugin set).

Strategy per SURVEY.md §4 implication (3): property tests comparing the
vectorized kernels to the slow per-pod oracle, on the CPU jax backend.
Parity is checked at full annotation-wire-format depth (the reference's 13
per-pod annotation payloads), not just placements.
"""

import random

import pytest

from kube_scheduler_simulator_tpu.engine import (
    EXACT,
    TPU32,
    BatchedScheduler,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.engine.engine import UnsupportedPluginError
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration
from kube_scheduler_simulator_tpu.sched.oracle import Oracle

from helpers import node, pod


def restricted_config(
    filters=("NodeUnschedulable", "NodeName", "NodeResourcesFit"),
    scores=(("NodeResourcesFit", 1), ("NodeResourcesBalancedAllocation", 1)),
    prefilters=("NodeResourcesFit",),
    prescores=("NodeResourcesFit", "NodeResourcesBalancedAllocation"),
):
    """A profile enabling only the named plugins (disable '*' + explicit
    enable, the reference's own plugin-set rewrite semantics)."""
    star = [{"name": "*"}]
    plugins = {
        "preFilter": {"disabled": star, "enabled": [{"name": n} for n in prefilters]},
        "filter": {"disabled": star, "enabled": [{"name": n} for n in filters]},
        "postFilter": {"disabled": star, "enabled": []},
        "preScore": {"disabled": star, "enabled": [{"name": n} for n in prescores]},
        "score": {
            "disabled": star,
            "enabled": [{"name": n, "weight": w} for n, w in scores],
        },
    }
    return SchedulerConfiguration.from_dict(
        {"profiles": [{"schedulerName": "default-scheduler", "plugins": plugins}]}
    )


def assert_parity(nodes, pods, config, policy=EXACT, **enc_kw):
    # object kinds both the oracle and the encoder consume
    shared = {
        k: enc_kw[k]
        for k in ("pvcs", "pvs", "storageclasses", "priorityclasses", "namespaces")
        if k in enc_kw
    }
    oracle = Oracle(
        [dict(n) for n in nodes],
        [dict(p) for p in pods],
        config,
        **{k: [dict(o) for o in v] for k, v in shared.items()},
    )
    want = oracle.schedule_all()
    enc = encode_cluster(nodes, pods, config, policy=policy, **enc_kw)
    eng = BatchedScheduler(enc)
    got = eng.results()
    assert len(got) == len(want)
    for w, g in zip(want, got):
        key = (w.pod_namespace, w.pod_name)
        assert (g.pod_namespace, g.pod_name) == key
        assert g.status == w.status, (key, g.status, w.status)
        assert g.selected_node == w.selected_node, key
        assert g.to_annotations() == w.to_annotations(), key
    return got


class TestM2Parity:
    def test_basic_spread_over_capacity(self):
        nodes = [
            node("n0", cpu="2", mem="4Gi"),
            node("n1", cpu="4", mem="8Gi"),
            node("n2", cpu="8", mem="16Gi"),
        ]
        pods = [pod(f"p{i}", cpu="500m", mem="512Mi") for i in range(10)]
        assert_parity(nodes, pods, restricted_config())

    def test_tpu32_policy_mi_granular(self):
        nodes = [node("n0", cpu="2", mem="4Gi"), node("n1", cpu="4", mem="8Gi")]
        pods = [pod(f"p{i}", cpu="250m", mem="256Mi") for i in range(8)]
        assert_parity(nodes, pods, restricted_config(), policy=TPU32)

    def test_unschedulable_pod_and_node(self):
        nodes = [
            node("n0", cpu="1", mem="1Gi"),
            node("n1", cpu="1", mem="1Gi", unschedulable=True),
        ]
        pods = [
            pod("fits", cpu="500m", mem="256Mi"),
            pod("too-big", cpu="16", mem="64Gi"),
            pod("tolerates", cpu="100m", mem="64Mi",
                tolerations=[{"operator": "Exists"}]),
        ]
        results = assert_parity(nodes, pods, restricted_config())
        by_name = {r.pod_name: r for r in results}
        assert by_name["too-big"].status == "Unschedulable"
        assert by_name["fits"].status == "Scheduled"

    def test_node_name_pinning(self):
        nodes = [node("n0"), node("n1")]
        pods = [
            pod("pinned", node_name="n1"),
            pod("ghost", node_name="gone"),  # names a nonexistent node
        ]
        # pods with a nodeName naming an existing node are pre-bound (not
        # scheduled); 'ghost' stays pending and fails NodeName everywhere.
        oracle = Oracle([dict(n) for n in nodes], [dict(p) for p in pods],
                        restricted_config())
        assert len(oracle.pending) == 1
        assert_parity(nodes, pods, restricted_config())

    def test_priority_order_and_bound_pods(self):
        nodes = [node("n0", cpu="2", mem="2Gi"), node("n1", cpu="2", mem="2Gi")]
        pods = [
            pod("low", cpu="1500m", mem="512Mi", priority=1),
            pod("high", cpu="1500m", mem="512Mi", priority=100),
            pod("bound", cpu="1", mem="1Gi", node_name="n0"),
        ]
        # 'high' schedules first (PrioritySort), 'bound' consumes n0 upfront.
        results = assert_parity(nodes, pods, restricted_config())
        by_name = {r.pod_name: r for r in results}
        assert by_name["high"].selected_node == "n1"

    def test_capacity_padding_invariance(self):
        nodes = [node("n0", cpu="2"), node("n1", cpu="4")]
        pods = [pod(f"p{i}", cpu="300m") for i in range(6)]
        a = assert_parity(nodes, pods, restricted_config())
        b = assert_parity(
            nodes, pods, restricted_config(), node_capacity=16, pod_capacity=32
        )
        for ra, rb in zip(a, b):
            assert ra.to_annotations() == rb.to_annotations()

    def test_strict_raises_on_unimplemented_plugin(self):
        # the full default set is supported; a plugin with no kernel is not
        cfg = restricted_config(filters=("NodeResourcesFit", "NoSuchPlugin"))
        enc = encode_cluster([node("n0")], [pod("p0")], cfg)
        with pytest.raises(UnsupportedPluginError):
            BatchedScheduler(enc)

    def test_most_allocated_strategy(self):
        cfg = restricted_config()
        cfg.profiles[0]["pluginConfig"] = [
            {
                "name": "NodeResourcesFit",
                "args": {
                    "scoringStrategy": {
                        "type": "MostAllocated",
                        "resources": [
                            {"name": "cpu", "weight": 1},
                            {"name": "memory", "weight": 3},
                        ],
                    }
                },
            }
        ]
        nodes = [node("n0", cpu="4", mem="8Gi"), node("n1", cpu="8", mem="8Gi")]
        pods = [pod(f"p{i}", cpu="1", mem="1Gi") for i in range(5)]
        assert_parity(nodes, pods, cfg)

    def test_requested_to_capacity_ratio_strategy(self):
        """RequestedToCapacityRatio (the third upstream scoringStrategy):
        broken-linear shape over utilization, integer Go semantics — incl.
        a DOWNWARD segment, whose negative interpolation product is where
        trunc-toward-zero (Go) and floor (python/jnp //) differ."""
        cfg = restricted_config()
        cfg.profiles[0]["pluginConfig"] = [
            {
                "name": "NodeResourcesFit",
                "args": {
                    "scoringStrategy": {
                        "type": "RequestedToCapacityRatio",
                        "resources": [
                            {"name": "cpu", "weight": 2},
                            {"name": "memory", "weight": 1},
                        ],
                        "requestedToCapacityRatio": {
                            "shape": [
                                {"utilization": 0, "score": 10},
                                {"utilization": 70, "score": 7},
                                {"utilization": 100, "score": 0},
                            ]
                        },
                    }
                },
            }
        ]
        nodes = [
            node("n0", cpu="4", mem="8Gi"),
            node("n1", cpu="8", mem="16Gi"),
            node("n2", cpu="2", mem="4Gi"),
        ]
        pods = [pod(f"p{i}", cpu="700m", mem="1.5Gi") for i in range(6)]
        results = assert_parity(nodes, pods, cfg)
        # the shape actually drove scores: a scheduled pod has a non-flat
        # NodeResourcesFit score column
        scored = [
            {n: int(v["NodeResourcesFit"]) for n, v in r.score.items()}
            for r in results
            if r.status == "Scheduled"
        ]
        assert any(len(set(s.values())) > 1 for s in scored)

    def test_rtcr_shape_helpers_match_go_semantics(self):
        from kube_scheduler_simulator_tpu.sched.oracle_plugins import (
            broken_linear,
            rtcr_shape,
        )

        shape = rtcr_shape(
            {
                "requestedToCapacityRatio": {
                    "shape": [
                        {"utilization": 0, "score": 10},
                        {"utilization": 100, "score": 0},
                    ]
                }
            }
        )
        assert shape == [(0, 100), (100, 0)]
        # descending segment: Go computes (u-0)*(0-100)/100 + 100 with
        # trunc division: u=33 → (33*-100)/100 = -33 → 67
        assert broken_linear(shape, 33) == 67
        assert broken_linear(shape, 0) == 100
        assert broken_linear(shape, 100) == 0
        assert broken_linear(shape, 150) == 0  # clamp right
        assert broken_linear([(20, 0), (80, 100)], 10) == 0  # clamp left
        # default shape when unspecified: 0→0, 100→100 (score 10 scaled)
        assert rtcr_shape({}) == [(0, 0), (100, 100)]

    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_clusters(self, seed):
        rng = random.Random(seed)
        n_nodes = rng.randint(2, 10)
        n_pods = rng.randint(5, 40)
        nodes = []
        for i in range(n_nodes):
            nodes.append(
                node(
                    f"n{i}",
                    cpu=f"{rng.randint(1, 16)}",
                    mem=f"{rng.randint(1, 32)}Gi",
                    pods=str(rng.choice([3, 10, 110])),
                    unschedulable=rng.random() < 0.15,
                )
            )
        pods = []
        for i in range(n_pods):
            kw = {}
            if rng.random() < 0.1:
                kw["node_name"] = f"n{rng.randint(0, n_nodes)}"  # may not exist
            if rng.random() < 0.3:
                kw["priority"] = rng.randint(0, 5)
            if rng.random() < 0.1:
                kw["tolerations"] = [{"operator": "Exists"}]
            pods.append(
                pod(
                    f"p{i}",
                    cpu=f"{rng.choice([100, 250, 500, 1000, 4000])}m",
                    mem=f"{rng.choice([64, 128, 512, 1024, 4096])}Mi",
                    **kw,
                )
            )
        assert_parity(nodes, pods, restricted_config())
        assert_parity(nodes, pods, restricted_config(), policy=TPU32)
