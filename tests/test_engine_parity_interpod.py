"""Parity: InterPodAffinity kernel vs oracle (M3b)."""

import random

import pytest

from kube_scheduler_simulator_tpu.engine import EXACT, TPU32

from helpers import node, pod
from test_engine_parity import assert_parity
from test_engine_parity_m3 import m3a_config


def ipa_config():
    cfg = m3a_config(
        extra_filters=("InterPodAffinity",),
        extra_scores=(("InterPodAffinity", 1),),
    )
    cfg.profile()["plugins"]["preScore"]["enabled"].append(
        {"name": "InterPodAffinity"}
    )
    return cfg


def zone_nodes():
    out = []
    for z in ("a", "b"):
        for i in range(2):
            out.append(node(f"n-{z}{i}", labels={
                "topology.kubernetes.io/zone": z,
                "kubernetes.io/hostname": f"n-{z}{i}"}))
    return out


def aff(required=None, preferred=None, anti_required=None, anti_preferred=None):
    out = {}
    pa = {}
    if required:
        pa["requiredDuringSchedulingIgnoredDuringExecution"] = required
    if preferred:
        pa["preferredDuringSchedulingIgnoredDuringExecution"] = preferred
    if pa:
        out["podAffinity"] = pa
    paa = {}
    if anti_required:
        paa["requiredDuringSchedulingIgnoredDuringExecution"] = anti_required
    if anti_preferred:
        paa["preferredDuringSchedulingIgnoredDuringExecution"] = anti_preferred
    if paa:
        out["podAntiAffinity"] = paa
    return out


def term(app, key="topology.kubernetes.io/zone", ns=None, ns_selector=None):
    t = {"topologyKey": key,
         "labelSelector": {"matchLabels": {"app": app}}}
    if ns is not None:
        t["namespaces"] = ns
    if ns_selector is not None:
        t["namespaceSelector"] = ns_selector
    return t


class TestInterPodAffinity:
    def test_required_affinity_colocation(self):
        nodes = zone_nodes()
        pods = [
            pod("db", labels={"app": "db"}, node_name="n-b0"),
            pod("web", labels={"app": "web"},
                affinity=aff(required=[term("db")])),  # must land in zone b
        ]
        results = assert_parity(nodes, pods, ipa_config())
        assert results[0].selected_node.startswith("n-b")

    def test_required_anti_affinity_exclusion(self):
        nodes = zone_nodes()
        pods = [
            pod("db", labels={"app": "db"}, node_name="n-a0"),
            pod("web", labels={"app": "web"},
                affinity=aff(anti_required=[term("db")])),  # avoid zone a
        ]
        results = assert_parity(nodes, pods, ipa_config())
        assert results[0].selected_node.startswith("n-b")

    def test_anti_affinity_chain_hostname(self):
        # classic one-replica-per-node chain: each pod anti-affines itself
        nodes = zone_nodes()
        pods = [
            pod(f"r{i}", labels={"app": "web"},
                affinity=aff(anti_required=[term("web", key="kubernetes.io/hostname")]))
            for i in range(6)  # only 4 nodes -> last two unschedulable
        ]
        results = assert_parity(nodes, pods, ipa_config())
        statuses = [r.status for r in results]
        assert statuses.count("Scheduled") == 4
        assert statuses.count("Unschedulable") == 2

    def test_existing_pods_anti_affinity_symmetry(self):
        nodes = zone_nodes()
        pods = [
            # bound pod that repels app=web in its zone
            pod("grumpy", labels={"app": "db"}, node_name="n-a0",
                affinity=aff(anti_required=[term("web")])),
            pod("web", labels={"app": "web"}),
        ]
        results = assert_parity(nodes, pods, ipa_config())
        assert results[0].selected_node.startswith("n-b")

    def test_first_pod_in_series_self_match(self):
        nodes = zone_nodes()
        # nothing matches anywhere, but the pod matches its own term -> pass
        pods = [pod("web", labels={"app": "web"},
                    affinity=aff(required=[term("web")]))]
        results = assert_parity(nodes, pods, ipa_config())
        assert results[0].status == "Scheduled"

    def test_first_pod_no_self_match_unschedulable(self):
        nodes = zone_nodes()
        pods = [pod("web", labels={"app": "web"},
                    affinity=aff(required=[term("db")]))]
        results = assert_parity(nodes, pods, ipa_config())
        assert results[0].status == "Unschedulable"

    def test_preferred_affinity_scoring(self):
        nodes = zone_nodes()
        pods = [
            pod("db", labels={"app": "db"}, node_name="n-b1"),
            pod("web", labels={"app": "web"}, affinity=aff(preferred=[
                {"weight": 50, "podAffinityTerm": term("db")}])),
            pod("loner", labels={"app": "loner"}, affinity=aff(anti_preferred=[
                {"weight": 80, "podAffinityTerm": term("db")}])),
        ]
        for policy in (EXACT, TPU32):
            results = assert_parity(nodes, pods, ipa_config(), policy=policy)
        by = {r.pod_name: r for r in results}
        assert by["web"].selected_node.startswith("n-b")
        assert by["loner"].selected_node.startswith("n-a")

    def test_hard_pod_affinity_weight_symmetry(self):
        nodes = zone_nodes()
        pods = [
            # bound pod with REQUIRED affinity toward app=web: symmetric
            # scoring pulls web toward it at hardPodAffinityWeight
            pod("clingy", labels={"app": "db"}, node_name="n-b0",
                affinity=aff(required=[term("web")])),
            pod("web", labels={"app": "web"}),
        ]
        results = assert_parity(nodes, pods, ipa_config())
        assert results[0].selected_node.startswith("n-b")

    def test_namespaces_scoping(self):
        nodes = zone_nodes()
        pods = [
            pod("other-ns-db", labels={"app": "db"}, ns="prod", node_name="n-a0"),
            pod("db", labels={"app": "db"}, node_name="n-b0"),
            # same-namespace term: only 'db' in default ns counts
            pod("web1", labels={"app": "web"},
                affinity=aff(required=[term("db")])),
            # explicit namespaces: targets prod
            pod("web2", labels={"app": "web"},
                affinity=aff(required=[term("db", ns=["prod"])])),
        ]
        results = assert_parity(nodes, pods, ipa_config())
        by = {r.pod_name: r for r in results}
        assert by["web1"].selected_node.startswith("n-b")
        assert by["web2"].selected_node.startswith("n-a")


class TestInterpodRandomized:
    @pytest.mark.parametrize("seed", range(5))
    def test_randomized(self, seed):
        rng = random.Random(3000 + seed)
        nodes = []
        for i in range(rng.randint(3, 6)):
            nodes.append(node(f"n{i}", cpu="8", labels={
                "topology.kubernetes.io/zone": rng.choice(["a", "b"]),
                "kubernetes.io/hostname": f"n{i}"}))
        apps = ["web", "db", "cache"]
        pods = []
        for i in range(rng.randint(8, 16)):
            app = rng.choice(apps)
            kw = {"labels": {"app": app}}
            r = rng.random()
            key = rng.choice(["topology.kubernetes.io/zone", "kubernetes.io/hostname"])
            target = rng.choice(apps)
            if r < 0.25:
                kw["affinity"] = aff(required=[term(target, key=key)])
            elif r < 0.45:
                kw["affinity"] = aff(anti_required=[term(target, key=key)])
            elif r < 0.6:
                kw["affinity"] = aff(preferred=[
                    {"weight": rng.randint(1, 100),
                     "podAffinityTerm": term(target, key=key)}])
            elif r < 0.7:
                kw["affinity"] = aff(anti_preferred=[
                    {"weight": rng.randint(1, 100),
                     "podAffinityTerm": term(target, key=key)}])
            pods.append(pod(f"p{i}", cpu="200m", mem="128Mi", **kw))
        assert_parity(nodes, pods, ipa_config(), policy=EXACT)
        assert_parity(nodes, pods, ipa_config(), policy=TPU32)


class TestFirstPodTopologyKeyGate:
    """Pins the upstream satisfyPodAffinity behavior: the first-pod-in-series
    special case (required affinity, nothing matches anywhere, pod matches
    its own terms) only passes on nodes that carry every requested topology
    key — keyless nodes fail the filter before the special case applies."""

    def _cluster(self):
        nodes = [
            node("keyed", labels={"topology.kubernetes.io/zone": "a"}),
            node("keyless", labels={}),
        ]
        pods = [pod(
            "first", cpu="100m", labels={"app": "self"},
            affinity={"podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "topologyKey": "topology.kubernetes.io/zone",
                    "labelSelector": {"matchLabels": {"app": "self"}},
                }]
            }},
        )]
        return nodes, pods

    def test_keyless_node_fails_filter(self):
        nodes, pods = self._cluster()
        results = assert_parity(nodes, pods, ipa_config())
        r = results[0]
        assert r.status == "Scheduled"
        assert r.selected_node == "keyed"
        assert (
            r.filter["keyless"]["InterPodAffinity"]
            == "node(s) didn't match pod affinity rules"
        )

    def test_all_nodes_keyless_unschedulable(self):
        nodes, pods = self._cluster()
        nodes = [n for n in nodes if n["metadata"]["name"] == "keyless"]
        results = assert_parity(nodes, pods, ipa_config())
        assert results[0].status == "Unschedulable"
