"""Parity: M3a plugin kernels (TaintToleration, NodeAffinity, NodePorts,
ImageLocality) vs the oracle, at annotation depth."""

import random

import pytest

from kube_scheduler_simulator_tpu.engine import EXACT, TPU32

from helpers import node, pod
from test_engine_parity import assert_parity, restricted_config


def m3a_config(extra_filters=(), extra_scores=()):
    return restricted_config(
        filters=(
            "NodeUnschedulable",
            "NodeName",
            "TaintToleration",
            "NodeAffinity",
            "NodePorts",
            "NodeResourcesFit",
        )
        + tuple(extra_filters),
        scores=(
            ("NodeResourcesBalancedAllocation", 1),
            ("ImageLocality", 1),
            ("NodeResourcesFit", 1),
            ("NodeAffinity", 1),
            ("TaintToleration", 1),
        )
        + tuple(extra_scores),
        prefilters=("NodeResourcesFit", "NodePorts"),
        prescores=(
            "TaintToleration",
            "NodeAffinity",
            "NodeResourcesFit",
            "NodeResourcesBalancedAllocation",
        ),
    )


class TestTaintToleration:
    def test_filter_and_score(self):
        nodes = [
            node("clean"),
            node("tainted", taints=[
                {"key": "dedicated", "value": "gpu", "effect": "NoSchedule"},
            ]),
            node("prefer-avoid", taints=[
                {"key": "spot", "value": "true", "effect": "PreferNoSchedule"},
            ]),
            node("multi", taints=[
                {"key": "a", "value": "1", "effect": "PreferNoSchedule"},
                {"key": "b", "value": "2", "effect": "NoExecute"},
                {"key": "c", "value": "3", "effect": "NoSchedule"},
            ]),
        ]
        pods = [
            pod("plain"),
            pod("tolerates-equal", tolerations=[
                {"key": "dedicated", "operator": "Equal", "value": "gpu",
                 "effect": "NoSchedule"},
            ]),
            pod("tolerates-exists", tolerations=[
                {"key": "dedicated", "operator": "Exists"},
                {"key": "b", "operator": "Exists"},
                {"key": "c", "operator": "Exists"},
            ]),
            pod("tolerates-all", tolerations=[{"operator": "Exists"}]),
            pod("wrong-value", tolerations=[
                {"key": "dedicated", "operator": "Equal", "value": "cpu"},
            ]),
            pod("effect-scoped", tolerations=[
                {"key": "b", "operator": "Exists", "effect": "NoExecute"},
                {"key": "c", "operator": "Exists", "effect": "NoSchedule"},
            ]),
        ]
        for policy in (EXACT, TPU32):
            assert_parity(nodes, pods, m3a_config(), policy=policy)


class TestNodeAffinity:
    def test_selector_and_required(self):
        nodes = [
            node("ssd-east", labels={"disk": "ssd", "zone": "east", "idx": "10"}),
            node("hdd-east", labels={"disk": "hdd", "zone": "east", "idx": "2"}),
            node("ssd-west", labels={"disk": "ssd", "zone": "west"}),
            node("bare"),
        ]
        aff_req = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {"matchExpressions": [
                            {"key": "disk", "operator": "In", "values": ["ssd"]},
                        ]},
                        {"matchExpressions": [
                            {"key": "zone", "operator": "NotIn", "values": ["west"]},
                            {"key": "disk", "operator": "Exists"},
                        ]},
                    ]
                }
            }
        }
        aff_num = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {"matchExpressions": [
                            {"key": "idx", "operator": "Gt", "values": ["5"]},
                        ]},
                    ]
                }
            }
        }
        aff_fields = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {"matchFields": [
                            {"key": "metadata.name", "operator": "In",
                             "values": ["bare"]},
                        ]},
                    ]
                }
            }
        }
        aff_pref = {
            "nodeAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {"weight": 10, "preference": {"matchExpressions": [
                        {"key": "disk", "operator": "In", "values": ["ssd"]},
                    ]}},
                    {"weight": 5, "preference": {"matchExpressions": [
                        {"key": "zone", "operator": "In", "values": ["east"]},
                    ]}},
                ]
            }
        }
        pods = [
            pod("sel", node_selector={"disk": "ssd"}),
            pod("sel-missing-key", node_selector={"gpu": "a100"}),
            pod("req-terms", affinity=aff_req),
            pod("req-numeric", affinity=aff_num),
            pod("req-fields", affinity=aff_fields),
            pod("preferred", affinity=aff_pref),
            pod("dne", affinity={
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {"matchExpressions": [
                                {"key": "disk", "operator": "DoesNotExist"},
                            ]},
                        ]
                    }
                }
            }),
        ]
        for policy in (EXACT, TPU32):
            assert_parity(nodes, pods, m3a_config(), policy=policy)


class TestNodePorts:
    def test_conflicts(self):
        nodes = [node("n0"), node("n1")]
        pods = [
            pod("web-a", ports=[{"hostPort": 80}]),
            pod("web-b", ports=[{"hostPort": 80}]),  # conflicts with web-a
            pod("udp", ports=[{"hostPort": 80, "protocol": "UDP"}]),  # no conflict
            pod("ip-specific", ports=[{"hostPort": 80, "hostIP": "10.0.0.1"}]),
            pod("other-port", ports=[{"hostPort": 8080}]),
        ]
        for policy in (EXACT, TPU32):
            results = assert_parity(nodes, pods, m3a_config(), policy=policy)
        by = {r.pod_name: r for r in results}
        assert by["web-a"].selected_node != by["web-b"].selected_node
        # the wildcard-ip 80 conflicts with the specific-ip 80 on both used
        # nodes once web-a/web-b hold them
        assert by["ip-specific"].status == "Unschedulable"

    def test_bound_pods_occupy_ports(self):
        nodes = [node("n0"), node("n1")]
        pods = [
            pod("existing", ports=[{"hostPort": 443}], node_name="n0"),
            pod("incoming", ports=[{"hostPort": 443}]),
        ]
        results = assert_parity(nodes, pods, m3a_config())
        assert results[0].selected_node == "n1"


class TestImageLocality:
    def test_score(self):
        big = 500 * 1024 * 1024
        nodes = [
            node("has-both", images=[
                {"names": ["nginx:latest"], "sizeBytes": big},
                {"names": ["redis"], "sizeBytes": big // 2},
            ]),
            node("has-one", images=[{"names": ["nginx"], "sizeBytes": big}]),
            node("has-none"),
        ]
        pods = [
            pod("uses-both", images=["nginx", "redis:latest"]),
            pod("uses-one", images=["nginx:latest"]),
            pod("uses-unknown", images=["mysql"]),
        ]
        for policy in (EXACT, TPU32):
            results = assert_parity(nodes, pods, m3a_config(), policy=policy)
        by = {r.pod_name: r for r in results}
        assert by["uses-both"].selected_node == "has-both"


class TestRandomizedM3a:
    @pytest.mark.parametrize("seed", range(6))
    def test_randomized(self, seed):
        rng = random.Random(1000 + seed)
        zones = ["a", "b", "c"]
        disks = ["ssd", "hdd"]
        n_nodes = rng.randint(3, 10)
        nodes = []
        for i in range(n_nodes):
            taints = []
            if rng.random() < 0.3:
                taints.append({
                    "key": rng.choice(["t1", "t2"]),
                    "value": rng.choice(["x", "y"]),
                    "effect": rng.choice(
                        ["NoSchedule", "PreferNoSchedule", "NoExecute"]),
                })
            images = []
            if rng.random() < 0.5:
                images.append({
                    "names": [rng.choice(["nginx", "redis", "mysql"])],
                    "sizeBytes": rng.randint(30, 900) * 1024 * 1024,
                })
            nodes.append(node(
                f"n{i}",
                cpu=f"{rng.randint(2, 16)}",
                mem=f"{rng.randint(2, 32)}Gi",
                labels={"zone": rng.choice(zones), "disk": rng.choice(disks)},
                taints=taints or None,
                images=images or None,
                unschedulable=rng.random() < 0.1,
            ))
        pods = []
        for i in range(rng.randint(10, 30)):
            kw = {}
            r = rng.random()
            if r < 0.2:
                kw["node_selector"] = {"zone": rng.choice(zones)}
            elif r < 0.4:
                kw["affinity"] = {"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{"matchExpressions": [{
                            "key": "disk",
                            "operator": rng.choice(["In", "NotIn"]),
                            "values": [rng.choice(disks)],
                        }]}]
                    }
                }}
            elif r < 0.55:
                kw["affinity"] = {"nodeAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [{
                        "weight": rng.randint(1, 100),
                        "preference": {"matchExpressions": [{
                            "key": "zone", "operator": "In",
                            "values": [rng.choice(zones)],
                        }]},
                    }]
                }}
            if rng.random() < 0.3:
                kw["tolerations"] = [{
                    "key": rng.choice(["t1", "t2"]),
                    "operator": rng.choice(["Exists", "Equal"]),
                    "value": rng.choice(["x", "y"]),
                }]
            if rng.random() < 0.25:
                kw["ports"] = [{"hostPort": rng.choice([80, 443, 8080])}]
            if rng.random() < 0.3:
                kw["images"] = [rng.choice(["nginx", "redis", "mysql"])]
            pods.append(pod(
                f"p{i}",
                cpu=f"{rng.choice([100, 500, 1000])}m",
                mem=f"{rng.choice([128, 512, 1024])}Mi",
                **kw,
            ))
        assert_parity(nodes, pods, m3a_config(), policy=EXACT)
        assert_parity(nodes, pods, m3a_config(), policy=TPU32)


class TestReviewEdgeCases:
    def test_match_fields_bogus_key(self):
        # oracle evaluates matchFields against {"metadata.name": name} only:
        # unknown field keys are absent (In misses, DoesNotExist matches).
        nodes = [node("n0"), node("n1")]
        for op in ("In", "DoesNotExist"):
            pods = [pod("p", affinity={"nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [{"matchFields": [{
                        "key": "metadata.bogus", "operator": op,
                        "values": ["n1"] if op == "In" else [],
                    }]}]
                }
            }})]
            assert_parity(nodes, pods, m3a_config())

    def test_unknown_toleration_operator(self):
        nodes = [node("n0", taints=[
            {"key": "k", "value": "v", "effect": "NoSchedule"}])]
        pods = [pod("p", tolerations=[
            {"key": "k", "operator": "Bogus", "value": "v",
             "effect": "NoSchedule"}])]
        results = assert_parity(nodes, pods, m3a_config())
        assert results[0].status == "Unschedulable"
