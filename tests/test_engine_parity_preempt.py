"""Parity: DefaultPreemption (PostFilter) kernel vs oracle (M3c)."""

import random

import pytest

from kube_scheduler_simulator_tpu.engine import EXACT, TPU32

from helpers import node, pod
from test_engine_parity import assert_parity, restricted_config


def preempt_config():
    cfg = restricted_config(
        filters=("NodeUnschedulable", "NodeName", "NodeResourcesFit"),
        scores=(("NodeResourcesFit", 1), ("NodeResourcesBalancedAllocation", 1)),
        prefilters=("NodeResourcesFit",),
        prescores=("NodeResourcesFit", "NodeResourcesBalancedAllocation"),
    )
    cfg.profile()["plugins"]["postFilter"]["enabled"].append(
        {"name": "DefaultPreemption"}
    )
    return cfg


class TestMaskedPreemptMode:
    """preempt_mode="masked" (the vmap-safe always-run gating) must be
    bit-identical to the default lax.cond mode — state AND trace."""

    def _contended(self):
        nodes = [node(f"n{i}", cpu="2", pods="8") for i in range(4)]
        pods = []
        for i in range(4):
            pods.append(
                pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}")
            )
        for i in range(3):
            pods.append(pod(f"high-{i}", cpu="1200m", priority=100))
        pods.append(pod("huge", cpu="4", priority=50))  # never fits
        return nodes, pods

    def test_trace_and_state_bitwise_equal(self):
        import numpy as np

        from kube_scheduler_simulator_tpu.engine import encode_cluster
        from kube_scheduler_simulator_tpu.engine.engine import BatchedScheduler

        nodes, pods = self._contended()
        enc = encode_cluster(nodes, pods, preempt_config(), policy=TPU32)
        cond = BatchedScheduler(enc)
        masked = BatchedScheduler(enc, preempt_mode="masked")
        st_c, tr_c = cond.run()
        st_m, tr_m = masked.run()
        np.testing.assert_array_equal(
            np.asarray(st_c.assignment), np.asarray(st_m.assignment)
        )
        for name, a, b in zip(
            ("pf_codes", "codes", "raw", "final", "sel", "did", "pcode",
             "vmask", "nominated", "codes2", "raw2", "final2", "sel2",
             "pcode2", "vmask2", "nominated2", "final_sel"),
            tr_c,
            tr_m,
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"trace slot {name}"
            )
        # the workload actually exercised preemption
        assert bool(np.asarray(tr_c[5]).any())

    def test_invalid_mode_rejected(self):
        from kube_scheduler_simulator_tpu.engine import encode_cluster
        from kube_scheduler_simulator_tpu.engine.engine import BatchedScheduler

        nodes, pods = self._contended()
        enc = encode_cluster(nodes, pods, preempt_config(), policy=TPU32)
        with pytest.raises(ValueError):
            BatchedScheduler(enc, preempt_mode="select")


class TestPreemption:
    def test_basic_preempt_and_retry(self):
        nodes = [node("n0", cpu="2"), node("n1", cpu="2")]
        pods = [
            pod("low-a", cpu="1500m", priority=1, node_name="n0"),
            pod("low-b", cpu="1500m", priority=1, node_name="n1"),
            pod("high", cpu="1500m", priority=100),
        ]
        results = assert_parity(nodes, pods, preempt_config())
        # two records for 'high': Nominated then Scheduled
        assert [r.status for r in results] == ["Nominated", "Scheduled"]
        assert results[0].nominated_node in ("n0", "n1")
        assert len(results[0].preemption_victims) == 1

    def test_rank_min_highest_victim_priority(self):
        nodes = [node("n0", cpu="2"), node("n1", cpu="2")]
        pods = [
            pod("vip", cpu="1500m", priority=50, node_name="n0"),
            pod("pleb", cpu="1500m", priority=1, node_name="n1"),
            pod("high", cpu="1500m", priority=100),
        ]
        results = assert_parity(nodes, pods, preempt_config())
        # prefers evicting the lower-priority victim set (n1)
        assert results[0].nominated_node == "n1"
        assert results[0].preemption_victims == ["default/pleb"]

    def test_reprieve_keeps_small_victims(self):
        # node has two low-priority pods; evicting just one frees enough
        nodes = [node("n0", cpu="3", pods="10")]
        pods = [
            pod("small", cpu="500m", priority=1, node_name="n0"),
            pod("big", cpu="2", priority=2, node_name="n0"),
            pod("high", cpu="2500m", priority=100),
        ]
        results = assert_parity(nodes, pods, preempt_config())
        by_status = [r.status for r in results]
        assert "Nominated" in by_status

    def test_no_lower_priority_pods(self):
        nodes = [node("n0", cpu="1")]
        pods = [
            pod("equal", cpu="800m", priority=100, node_name="n0"),
            pod("high", cpu="800m", priority=100),
        ]
        results = assert_parity(nodes, pods, preempt_config())
        assert results[0].status == "Unschedulable"

    def test_preemption_would_not_help(self):
        nodes = [node("n0", cpu="1")]
        pods = [
            pod("low", cpu="500m", priority=1, node_name="n0"),
            pod("huge", cpu="4", priority=100),  # doesn't fit even empty
        ]
        results = assert_parity(nodes, pods, preempt_config())
        assert results[0].status == "Unschedulable"

    def test_priorityclass_resolution(self):
        nodes = [node("n0", cpu="2")]
        pcs = [
            {"metadata": {"name": "critical"}, "value": 1000},
            {"metadata": {"name": "batch"}, "value": 1, "globalDefault": True},
        ]
        pods = [
            pod("old", cpu="1500m", node_name="n0"),  # batch via globalDefault
            pod("vip", cpu="1500m", priority_class="critical"),
        ]
        from kube_scheduler_simulator_tpu.engine import encode_cluster, BatchedScheduler
        from kube_scheduler_simulator_tpu.sched.oracle import Oracle

        cfg = preempt_config()
        oracle = Oracle([dict(n) for n in nodes], [dict(p) for p in pods], cfg,
                        priorityclasses=[dict(p) for p in pcs])
        want = oracle.schedule_all()
        enc = encode_cluster(nodes, pods, cfg, priorityclasses=pcs, policy=EXACT)
        from kube_scheduler_simulator_tpu.engine.engine import BatchedScheduler as BS
        got = BS(enc).results()
        assert len(got) == len(want)
        for w, g in zip(want, got):
            assert g.status == w.status
            assert g.selected_node == w.selected_node
            assert g.to_annotations() == w.to_annotations()
        assert want[0].status == "Nominated"

    def test_cascade_preemption_multiple_pods(self):
        nodes = [node("n0", cpu="2"), node("n1", cpu="2")]
        pods = [
            pod("l0", cpu="1500m", priority=1, node_name="n0"),
            pod("l1", cpu="1500m", priority=2, node_name="n1"),
            pod("h0", cpu="1500m", priority=100),
            pod("h1", cpu="1500m", priority=100),
        ]
        assert_parity(nodes, pods, preempt_config())

    @pytest.mark.parametrize("seed", range(5))
    def test_randomized_preemption(self, seed):
        rng = random.Random(4000 + seed)
        n_nodes = rng.randint(2, 5)
        nodes = [node(f"n{i}", cpu=f"{rng.randint(1, 4)}") for i in range(n_nodes)]
        pods = []
        # bound low-priority filler
        for i in range(rng.randint(2, 6)):
            pods.append(pod(
                f"f{i}", cpu=f"{rng.choice([500, 1000, 1500])}m",
                priority=rng.randint(0, 10),
                node_name=f"n{rng.randint(0, n_nodes - 1)}",
            ))
        # incoming mixed-priority pods
        for i in range(rng.randint(3, 8)):
            pods.append(pod(
                f"p{i}", cpu=f"{rng.choice([500, 1000, 2000])}m",
                priority=rng.choice([0, 5, 50, 100]),
            ))
        # skip manifests that over-commit a node at encode time (bound pods
        # may exceed capacity; that's legal and both sides must agree)
        assert_parity(nodes, pods, preempt_config(), policy=EXACT)
        assert_parity(nodes, pods, preempt_config(), policy=TPU32)


def row_config(filters, prefilters=("NodeResourcesFit",)):
    cfg = restricted_config(filters=filters, prefilters=prefilters)
    cfg.profile()["plugins"]["postFilter"]["enabled"].append(
        {"name": "DefaultPreemption"}
    )
    return cfg


class TestPreemptionRowFilters:
    """Parity for the state-dependent preemption row filters beyond
    NodeResourcesFit (engine/preempt.py _PortsRow/_SpreadRow/_InterpodRow):
    victim removal must be visible to ports/spread/inter-pod feasibility
    during the dry run, exactly as the oracle's _feasible_after_removal."""

    def test_ports_row_eviction_frees_port(self):
        cfg = row_config(("NodeResourcesFit", "NodePorts"),
                         prefilters=("NodeResourcesFit", "NodePorts"))
        nodes = [node("n0", cpu="4")]
        pods = [
            pod("holder", cpu="100m", priority=1, node_name="n0",
                ports=[{"containerPort": 80, "hostPort": 80}]),
            pod("high", cpu="100m", priority=100,
                ports=[{"containerPort": 80, "hostPort": 80}]),
        ]
        results = assert_parity(nodes, pods, cfg)
        assert results[0].status == "Nominated"
        assert results[0].preemption_victims == ["default/holder"]
        assert results[1].status == "Scheduled"

    def test_ports_row_no_preempt_when_port_held_by_higher(self):
        cfg = row_config(("NodeResourcesFit", "NodePorts"),
                         prefilters=("NodeResourcesFit", "NodePorts"))
        nodes = [node("n0", cpu="4")]
        pods = [
            pod("holder", cpu="100m", priority=200, node_name="n0",
                ports=[{"containerPort": 80, "hostPort": 80}]),
            pod("high", cpu="100m", priority=100,
                ports=[{"containerPort": 80, "hostPort": 80}]),
        ]
        results = assert_parity(nodes, pods, cfg)
        assert results[0].status == "Unschedulable"

    def test_spread_row_dry_run_counts(self):
        cfg = row_config(("NodeResourcesFit", "PodTopologySpread"))
        spread = [{
            "maxSkew": 1, "topologyKey": "zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "x"}},
        }]
        nodes = [
            node("n0", cpu="1", labels={"zone": "z0"}),
            node("n1", cpu="1", labels={"zone": "z1"}),
        ]
        pods = [
            pod("a1", cpu="600m", priority=1, node_name="n0", labels={"app": "x"}),
            pod("a2", cpu="400m", priority=1, node_name="n0", labels={"app": "x"}),
            pod("b1", cpu="1", priority=1, node_name="n1", labels={"app": "x"}),
            pod("hi", cpu="500m", priority=10, labels={"app": "x"}, spread=spread),
        ]
        results = assert_parity(nodes, pods, cfg)
        assert results[0].status == "Nominated"

    def test_interpod_row_anti_affinity_victim(self):
        cfg = row_config(("NodeResourcesFit", "InterPodAffinity"))
        anti = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "topologyKey": "kubernetes.io/hostname",
                    "labelSelector": {"matchLabels": {"app": "db"}},
                }]
            }
        }
        nodes = [node("n0", cpu="4", labels={"kubernetes.io/hostname": "n0"})]
        pods = [
            pod("dbpod", cpu="100m", priority=1, node_name="n0",
                labels={"app": "db"}),
            pod("high", cpu="100m", priority=100, affinity=anti),
        ]
        results = assert_parity(nodes, pods, cfg)
        assert results[0].status == "Nominated"
        assert results[0].preemption_victims == ["default/dbpod"]
        assert results[1].status == "Scheduled"

    @pytest.mark.parametrize("seed", range(3))
    def test_randomized_full_row_set(self, seed):
        """Randomized clusters with ports + spread + inter-pod constraints
        active during preemption, both dtype policies."""
        cfg = row_config(
            ("NodeUnschedulable", "NodeName", "NodeResourcesFit", "NodePorts",
             "PodTopologySpread", "InterPodAffinity"),
            prefilters=("NodeResourcesFit", "NodePorts"),
        )
        rng = random.Random(7000 + seed)
        n_nodes = rng.randint(2, 4)
        nodes = [
            node(f"n{i}", cpu=f"{rng.randint(1, 3)}",
                 labels={"zone": f"z{i % 2}", "kubernetes.io/hostname": f"n{i}"})
            for i in range(n_nodes)
        ]
        spread = [{
            "maxSkew": 1, "topologyKey": "zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "x"}},
        }]
        anti = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "topologyKey": "zone",
                    "labelSelector": {"matchLabels": {"app": "y"}},
                }]
            }
        }
        pods = []
        for i in range(rng.randint(2, 5)):
            pods.append(pod(
                f"f{i}", cpu=f"{rng.choice([500, 1000])}m",
                priority=rng.randint(0, 10),
                node_name=f"n{rng.randint(0, n_nodes - 1)}",
                labels={"app": rng.choice(["x", "y", "z"])},
                ports=[{"containerPort": 80, "hostPort": 8000 + (i % 2)}]
                if rng.random() < 0.5 else None,
            ))
        for i in range(rng.randint(3, 6)):
            kind = rng.random()
            pods.append(pod(
                f"p{i}", cpu=f"{rng.choice([500, 1000, 1500])}m",
                priority=rng.choice([0, 50, 100]),
                labels={"app": rng.choice(["x", "y"])},
                spread=spread if kind < 0.4 else None,
                affinity=anti if 0.4 <= kind < 0.7 else None,
                ports=[{"containerPort": 80, "hostPort": 8000}]
                if kind >= 0.9 else None,
            ))
        assert_parity(nodes, pods, cfg, policy=EXACT)
        assert_parity(nodes, pods, cfg, policy=TPU32)
