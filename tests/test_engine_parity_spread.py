"""Parity: PodTopologySpread kernel vs oracle (M3b)."""

import random

import pytest

from kube_scheduler_simulator_tpu.engine import EXACT, TPU32

from helpers import node, pod
from test_engine_parity import assert_parity
from test_engine_parity_m3 import m3a_config


def spread_config():
    cfg = m3a_config(
        extra_filters=("PodTopologySpread",),
        extra_scores=(("PodTopologySpread", 2),),
    )
    cfg.profile()["plugins"]["preScore"]["enabled"].append(
        {"name": "PodTopologySpread"}
    )
    return cfg


def zone_nodes(n_per_zone=2, zones=("a", "b", "c"), cpu="8"):
    out = []
    for z in zones:
        for i in range(n_per_zone):
            out.append(
                node(
                    f"n-{z}{i}",
                    cpu=cpu,
                    labels={
                        "topology.kubernetes.io/zone": z,
                        "kubernetes.io/hostname": f"n-{z}{i}",
                    },
                )
            )
    return out


def spread_pod(name, max_skew=1, when="DoNotSchedule", key="topology.kubernetes.io/zone",
               labels=None, selector_labels=None, **kw):
    labels = labels or {"app": "web"}
    return pod(
        name,
        labels=labels,
        spread=[{
            "maxSkew": max_skew,
            "topologyKey": key,
            "whenUnsatisfiable": when,
            "labelSelector": {"matchLabels": selector_labels or {"app": "web"}},
        }],
        **kw,
    )


class TestSpreadFilter:
    def test_hard_spread_across_zones(self):
        nodes = zone_nodes()
        pods = [spread_pod(f"w{i}") for i in range(9)]
        results = assert_parity(nodes, pods, spread_config())
        # pods must spread: each zone gets 3
        zones = {}
        for r in results:
            z = r.selected_node.split("-")[1][0]
            zones[z] = zones.get(z, 0) + 1
        assert zones == {"a": 3, "b": 3, "c": 3}

    def test_missing_topology_label(self):
        nodes = zone_nodes() + [node("unlabeled")]
        pods = [spread_pod(f"w{i}") for i in range(4)]
        assert_parity(nodes, pods, spread_config())

    def test_hard_spread_becomes_unschedulable(self):
        # one zone saturated by bound pods: maxSkew 1 forces alternation and
        # capacity limits eventually make pods unschedulable
        nodes = zone_nodes(n_per_zone=1, zones=("a", "b"), cpu="1")
        pods = [spread_pod("pre-a", node_name="n-a0")] + [
            spread_pod(f"w{i}", cpu="400m") for i in range(4)
        ]
        assert_parity(nodes, pods, spread_config())

    def test_hostname_spread(self):
        nodes = zone_nodes(n_per_zone=2, zones=("a",))
        pods = [
            spread_pod(f"w{i}", key="kubernetes.io/hostname") for i in range(4)
        ]
        assert_parity(nodes, pods, spread_config())


class TestSpreadScore:
    def test_soft_spread(self):
        nodes = zone_nodes()
        pods = [spread_pod(f"w{i}", when="ScheduleAnyway", max_skew=2)
                for i in range(7)]
        for policy in (EXACT, TPU32):
            assert_parity(nodes, pods, spread_config(), policy=policy)

    def test_system_defaults_no_explicit_constraints(self):
        nodes = zone_nodes()
        pods = [pod(f"w{i}", labels={"app": "web"}) for i in range(5)]
        assert_parity(nodes, pods, spread_config())

    def test_mixed_hard_soft(self):
        nodes = zone_nodes()
        pods = []
        for i in range(6):
            pods.append(pod(
                f"w{i}", labels={"app": "web"},
                spread=[
                    {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
                     "whenUnsatisfiable": "DoNotSchedule",
                     "labelSelector": {"matchLabels": {"app": "web"}}},
                    {"maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
                     "whenUnsatisfiable": "ScheduleAnyway",
                     "labelSelector": {"matchLabels": {"app": "web"}}},
                ],
            ))
        for policy in (EXACT, TPU32):
            assert_parity(nodes, pods, spread_config(), policy=policy)


class TestSpreadRandomized:
    @pytest.mark.parametrize("seed", range(5))
    def test_randomized(self, seed):
        rng = random.Random(2000 + seed)
        zones = ["a", "b"]
        nodes = []
        for i in range(rng.randint(3, 8)):
            labels = {"kubernetes.io/hostname": f"n{i}"}
            if rng.random() < 0.8:
                labels["topology.kubernetes.io/zone"] = rng.choice(zones)
            nodes.append(node(f"n{i}", cpu=f"{rng.randint(2, 8)}", labels=labels))
        apps = ["web", "db"]
        pods = []
        for i in range(rng.randint(8, 20)):
            app = rng.choice(apps)
            kw = {"labels": {"app": app}}
            r = rng.random()
            if r < 0.4:
                kw["spread"] = [{
                    "maxSkew": rng.randint(1, 2),
                    "topologyKey": "topology.kubernetes.io/zone",
                    "whenUnsatisfiable": rng.choice(
                        ["DoNotSchedule", "ScheduleAnyway"]),
                    "labelSelector": {"matchLabels": {"app": app}},
                }]
            elif r < 0.55:
                kw["spread"] = [{
                    "maxSkew": 1,
                    "topologyKey": "kubernetes.io/hostname",
                    "whenUnsatisfiable": rng.choice(
                        ["DoNotSchedule", "ScheduleAnyway"]),
                    "labelSelector": {
                        "matchExpressions": [
                            {"key": "app", "operator": "In", "values": apps},
                        ]
                    },
                }]
            pods.append(pod(f"p{i}", cpu="200m", mem="128Mi", **kw))
        assert_parity(nodes, pods, spread_config(), policy=EXACT)
        assert_parity(nodes, pods, spread_config(), policy=TPU32)
