"""Parity: volume-family kernels (VolumeBinding, VolumeZone,
VolumeRestrictions, EBS/GCEPD/Azure limits) vs the oracle, at annotation
depth — and strict acceptance of the full default plugin configuration."""

import random

from kube_scheduler_simulator_tpu.engine import (
    EXACT,
    TPU32,
    BatchedScheduler,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.engine.engine import supported_config
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration

from helpers import node, pod
from test_engine_parity import assert_parity, restricted_config


def pvc(name, ns="default", sc=None, volume_name=None, modes=None,
        storage="1Gi", selector=None):
    spec = {"resources": {"requests": {"storage": storage}}}
    if sc is not None:
        spec["storageClassName"] = sc
    if volume_name:
        spec["volumeName"] = volume_name
    if modes:
        spec["accessModes"] = list(modes)
    if selector:
        spec["selector"] = selector
    return {"metadata": {"name": name, "namespace": ns}, "spec": spec}


def pv(name, sc=None, capacity="10Gi", modes=("ReadWriteOnce",),
       claim_ref=None, node_affinity=None, labels=None):
    spec = {
        "capacity": {"storage": capacity},
        "accessModes": list(modes),
    }
    if sc is not None:
        spec["storageClassName"] = sc
    if claim_ref:
        spec["claimRef"] = claim_ref
    if node_affinity:
        spec["nodeAffinity"] = node_affinity
    return {"metadata": {"name": name, "labels": labels or {}}, "spec": spec}


def storageclass(name, mode="Immediate"):
    return {"metadata": {"name": name}, "volumeBindingMode": mode}


def claim_vol(claim):
    return {"name": f"v-{claim}", "persistentVolumeClaim": {"claimName": claim}}


def vol_config(extra_filters=(), postfilters=()):
    cfg = restricted_config(
        filters=(
            "NodeUnschedulable",
            "NodeName",
            "NodeResourcesFit",
            "VolumeRestrictions",
            "EBSLimits",
            "GCEPDLimits",
            "NodeVolumeLimits",
            "AzureDiskLimits",
            "VolumeBinding",
            "VolumeZone",
        )
        + tuple(extra_filters),
        scores=(("NodeResourcesFit", 1), ("NodeResourcesBalancedAllocation", 1)),
        prefilters=("NodeResourcesFit", "VolumeRestrictions", "VolumeBinding",
                    "VolumeZone"),
        prescores=("NodeResourcesFit", "NodeResourcesBalancedAllocation"),
    )
    if postfilters:
        d = cfg.to_dict()
        d["profiles"][0]["plugins"]["postFilter"]["enabled"] = [
            {"name": n} for n in postfilters
        ]
        return SchedulerConfiguration.from_dict(d)
    return cfg


ZONE = "topology.kubernetes.io/zone"


class TestVolumeBinding:
    def test_missing_pvc_fails_prefilter(self):
        nodes = [node("n0")]
        pods = [pod("p0", volumes=[claim_vol("ghost")]), pod("ok")]
        assert_parity(nodes, pods, vol_config())

    def test_bound_pv_node_affinity(self):
        aff = {
            "required": {
                "nodeSelectorTerms": [
                    {"matchExpressions": [
                        {"key": ZONE, "operator": "In", "values": ["z1"]},
                    ]}
                ]
            }
        }
        nodes = [node("in-zone", labels={ZONE: "z1"}),
                 node("off-zone", labels={ZONE: "z2"})]
        pods = [pod("p0", volumes=[claim_vol("data")])]
        kw = dict(
            pvcs=[pvc("data", volume_name="pv-data")],
            pvs=[pv("pv-data", node_affinity=aff)],
        )
        for policy in (EXACT, TPU32):
            got = assert_parity(nodes, pods, vol_config(), policy=policy, **kw)
        assert got[0].selected_node == "in-zone"

    def test_wait_for_first_consumer_skips(self):
        nodes = [node("n0")]
        pods = [pod("p0", volumes=[claim_vol("lazy")])]
        kw = dict(
            pvcs=[pvc("lazy", sc="wffc")],
            storageclasses=[storageclass("wffc", mode="WaitForFirstConsumer")],
        )
        got = assert_parity(nodes, pods, vol_config(), **kw)
        assert got[0].status == "Scheduled"

    def test_immediate_binding_needs_compatible_pv(self):
        nodes = [node("n0"), node("n1")]
        # claim asks 5Gi from sc "std": only a too-small PV exists
        pods = [pod("p0", volumes=[claim_vol("big")]),
                pod("p1", volumes=[claim_vol("ok")])]
        kw = dict(
            pvcs=[pvc("big", sc="std", storage="5Gi"),
                  pvc("ok", sc="std", storage="1Gi")],
            pvs=[pv("small", sc="std", capacity="2Gi")],
            storageclasses=[storageclass("std")],
        )
        got = assert_parity(nodes, pods, vol_config(), **kw)
        by = {r.pod_name: r for r in got}
        assert by["p0"].status == "Unschedulable"
        assert by["p1"].status == "Scheduled"


class TestVolumeZone:
    def test_zone_conflict(self):
        nodes = [node("a", labels={ZONE: "z1"}), node("b", labels={ZONE: "z2"})]
        pods = [pod("p0", volumes=[claim_vol("zonal")])]
        kw = dict(
            pvcs=[pvc("zonal", volume_name="pv-z")],
            pvs=[pv("pv-z", labels={ZONE: "z1"})],
        )
        for policy in (EXACT, TPU32):
            got = assert_parity(nodes, pods, vol_config(), policy=policy, **kw)
        assert got[0].selected_node == "a"

    def test_multi_zone_value(self):
        nodes = [node("a", labels={ZONE: "z1"}), node("b", labels={ZONE: "z3"})]
        pods = [pod("p0", volumes=[claim_vol("multi")])]
        kw = dict(
            pvcs=[pvc("multi", volume_name="pv-m")],
            pvs=[pv("pv-m", labels={ZONE: "z1__z2"})],
        )
        got = assert_parity(nodes, pods, vol_config(), **kw)
        assert got[0].selected_node == "a"


class TestVolumeRestrictions:
    def test_rwop_claim_in_use(self):
        nodes = [node("n0"), node("n1")]
        pods = [
            pod("holder", node_name="n0", volumes=[claim_vol("solo")]),
            pod("wants", volumes=[claim_vol("solo")]),
        ]
        kw = dict(pvcs=[pvc("solo", modes=("ReadWriteOncePod",),
                             volume_name="pv-s")],
                  pvs=[pv("pv-s")])
        got = assert_parity(nodes, pods, vol_config(), **kw)
        by = {r.pod_name: r for r in got}
        assert by["wants"].status == "Unschedulable"

    def test_rwop_freed_when_sequenced(self):
        # claim not yet used by any bound pod -> first pending pod takes it,
        # second fails (sequential semantics: pod i sees pod i-1's binding)
        nodes = [node("n0"), node("n1")]
        pods = [
            pod("first", priority=10, volumes=[claim_vol("solo")]),
            pod("second", priority=1, volumes=[claim_vol("solo")]),
        ]
        kw = dict(pvcs=[pvc("solo", modes=("ReadWriteOncePod",),
                             volume_name="pv-s")],
                  pvs=[pv("pv-s")])
        got = assert_parity(nodes, pods, vol_config(), **kw)
        by = {r.pod_name: r for r in got}
        assert by["first"].status == "Scheduled"
        assert by["second"].status == "Unschedulable"

    def test_disk_conflict_and_readonly(self):
        gce_rw = {"name": "d", "gcePersistentDisk": {"pdName": "disk-1"}}
        gce_ro = {"name": "d",
                  "gcePersistentDisk": {"pdName": "disk-1", "readOnly": True}}
        nodes = [node("n0"), node("n1")]
        pods = [
            pod("holder-ro", node_name="n0", volumes=[gce_ro]),
            pod("rw-pod", volumes=[gce_rw]),     # conflicts with ro on n0
            pod("ro-pod", volumes=[gce_ro]),     # ro+ro is fine anywhere
        ]
        for policy in (EXACT, TPU32):
            got = assert_parity(nodes, pods, vol_config(), policy=policy)
        by = {r.pod_name: r for r in got}
        assert by["rw-pod"].selected_node == "n1"

    def test_rbd_and_iscsi_identity(self):
        rbd = {"name": "r", "rbd": {"pool": "rp", "image": "img1"}}
        nodes = [node("n0"), node("n1")]
        pods = [
            pod("a", volumes=[rbd]),
            pod("b", volumes=[dict(rbd)]),
        ]
        got = assert_parity(nodes, pods, vol_config())
        # second pod must avoid the first pod's node
        sel = {r.pod_name: r.selected_node for r in got}
        assert sel["a"] != sel["b"]


class TestVolumeLimits:
    def test_gce_pd_limit(self):
        def disks(tag, k):
            return [
                {"name": f"{tag}-{i}",
                 "gcePersistentDisk": {"pdName": f"{tag}-{i}", "readOnly": True}}
                for i in range(k)
            ]

        nodes = [node("n0")]
        pods = [
            pod("bulk", node_name="n0", volumes=disks("a", 10)),
            pod("fits", volumes=disks("b", 6)),     # 10+6 = 16 (limit)
            pod("over", volumes=disks("c", 7)),     # 16+7 > 16 after 'fits'
        ]
        for policy in (EXACT, TPU32):
            got = assert_parity(nodes, pods, vol_config(), policy=policy)
        by = {r.pod_name: r for r in got}
        assert by["fits"].status == "Scheduled"
        assert by["over"].status == "Unschedulable"

    def test_azure_and_ebs_types_counted_separately(self):
        vols = [{"name": "az", "azureDisk": {"diskName": "d1"}},
                {"name": "eb", "awsElasticBlockStore": {"volumeID": "v1",
                                                        "readOnly": True}}]
        nodes = [node("n0")]
        pods = [pod("mixed", volumes=vols), pod("plain")]
        assert_parity(nodes, pods, vol_config())


class TestVolumePreemption:
    def test_preempt_disk_holder(self):
        gce = {"name": "d", "gcePersistentDisk": {"pdName": "hot-disk"}}
        nodes = [node("only")]
        pods = [
            pod("victim", priority=1, node_name="only", volumes=[dict(gce)]),
            pod("urgent", priority=100, volumes=[dict(gce)]),
        ]
        cfg = vol_config(postfilters=("DefaultPreemption",))
        got = assert_parity(nodes, pods, cfg)
        by_status = [(r.pod_name, r.status) for r in got]
        assert ("urgent", "Nominated") in by_status

    def test_preempt_rwop_holder(self):
        nodes = [node("only")]
        pods = [
            pod("victim", priority=1, node_name="only",
                volumes=[claim_vol("solo")]),
            pod("urgent", priority=100, volumes=[claim_vol("solo")]),
        ]
        kw = dict(pvcs=[pvc("solo", modes=("ReadWriteOncePod",),
                             volume_name="pv-s")],
                  pvs=[pv("pv-s")])
        cfg = vol_config(postfilters=("DefaultPreemption",))
        got = assert_parity(nodes, pods, cfg, **kw)
        assert any(r.status == "Nominated" for r in got)

    def test_preempt_volume_limit_holder(self):
        def disks(tag, k):
            return [
                {"name": f"{tag}-{i}",
                 "gcePersistentDisk": {"pdName": f"{tag}-{i}", "readOnly": True}}
                for i in range(k)
            ]

        nodes = [node("only")]
        pods = [
            pod("victim", priority=1, node_name="only", volumes=disks("a", 16)),
            pod("urgent", priority=100, volumes=disks("b", 1)),
        ]
        cfg = vol_config(postfilters=("DefaultPreemption",))
        got = assert_parity(nodes, pods, cfg)
        assert any(r.status == "Nominated" for r in got)


class TestFullDefaultConfig:
    def test_strict_accepts_default(self):
        """The engine's supported set now covers the entire default
        KubeSchedulerConfiguration (reference default filter set:
        simulator/scheduler/config/plugin.go:38-59)."""
        cfg = SchedulerConfiguration.default()
        nodes = [node(f"n{i}") for i in range(3)]
        pods = [pod(f"p{i}") for i in range(4)]
        enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
        BatchedScheduler(enc, strict=True)  # must not raise

    def test_supported_config_is_default_sets(self):
        sup = supported_config()
        dflt = SchedulerConfiguration.default()
        for point in ("preFilter", "filter", "postFilter", "preScore", "score"):
            assert sup.enabled(point) == dflt.enabled(point), point

    def test_default_config_parity_with_volumes(self):
        rng = random.Random(11)
        zones = ["z1", "z2"]
        nodes = [
            node(f"n{i}", cpu="4", mem="8Gi", labels={ZONE: zones[i % 2]})
            for i in range(4)
        ]
        pvs_ = [pv(f"pv{i}", sc="std", capacity="10Gi",
                   labels={ZONE: zones[i % 2]}) for i in range(3)]
        pvcs_ = (
            [pvc(f"c{i}", sc="std", storage="1Gi") for i in range(2)]
            + [pvc("zonal", volume_name="pv0")]
        )
        sc = [storageclass("std")]
        pods = []
        for i in range(12):
            vols = []
            r = rng.random()
            if r < 0.3:
                vols.append(claim_vol(rng.choice(["c0", "c1", "zonal"])))
            elif r < 0.5:
                vols.append({"name": "d", "gcePersistentDisk": {
                    "pdName": f"disk-{rng.randrange(3)}",
                    "readOnly": rng.random() < 0.5}})
            pods.append(pod(f"p{i}", cpu="200m", mem="256Mi",
                            volumes=vols or None,
                            priority=rng.choice([0, 0, 10])))
        cfg = SchedulerConfiguration.default()
        for policy in (EXACT, TPU32):
            assert_parity(nodes, pods, cfg, policy=policy,
                          pvcs=pvcs_, pvs=pvs_, storageclasses=sc)
