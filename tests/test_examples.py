"""The examples/ specs must stay runnable: snapshot imports cleanly and
schedules, the scenario and sweep specs run to Succeeded through the
batch runner (the same path the HTTP /api/v1/scenario route uses)."""

import json
import os

from kube_scheduler_simulator_tpu.scenario.batch import load_jobs, run_batch
from kube_scheduler_simulator_tpu.server.service import SimulatorService

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def test_snapshot_imports_and_schedules():
    with open(os.path.join(EXAMPLES, "snapshot.json")) as f:
        snap = json.load(f)
    svc = SimulatorService()
    errors = svc.import_(snap, ignore_err=True)
    assert errors == []
    # the deployment extension key expands through the controllers
    svc.run_controllers()
    pods = svc.store.list("pods")
    names = {p["metadata"]["name"] for p in pods}
    assert {"web-a", "web-b", "batch-1"} <= names
    assert sum(1 for n in names if n.startswith("workers-")) == 3
    results = svc.scheduler.schedule()
    by_name = {r.pod_name: r for r in results}
    # the nodeSelector-pinned critical pod lands on the big node
    assert by_name["batch-1"].selected_node == "big-0"
    assert all(
        r.status == "Scheduled" for r in results
    ), [(r.pod_name, r.status) for r in results]
    # the PV controller bound the claim
    assert svc.store.get("pvs", "vol-0")["spec"]["claimRef"]["name"] == "data"


def test_scenario_and_sweep_examples_run(tmp_path):
    jobs = load_jobs(os.path.join(EXAMPLES, "jobs"))
    by_name = {j.name: j for j in jobs}
    assert {"scenario", "sweep"} <= set(by_name)
    assert len(jobs) == 2  # snapshot.json must NOT be picked up as a job
    results = run_batch(jobs, out_dir=str(tmp_path))
    assert results["scenario"]["phase"] == "Succeeded", results["scenario"]
    # the scenario really exercised preemption + the deployment
    t = results["scenario"]["timeline"]
    assert any(
        e["type"] == "Delete" and e["payload"].get("reason") == "preempted"
        for e in t["1"]
    )
    summary = results["scenario"]["summary"]
    assert summary["pods"]["preempted"] == 1
    assert summary["pods"]["pending"] == 0
    # sweep: four variants, everything placed in each
    sweep = results["sweep"]
    assert sweep["phase"] == "Succeeded"
    assert len(sweep["variants"]) == 4
    for v in sweep["variants"]:
        assert v["scheduled"] == 4 and v["unschedulable"] == 0
    # result files landed (KEP-184 file contract)
    assert (tmp_path / "scenario.result.json").exists()
    assert (tmp_path / "sweep.result.json").exists()


def test_chaos_example_runs(tmp_path):
    """The chaos timeline (`make lifecycle-smoke`'s spec) runs to
    Succeeded with its node-failure evictions all re-placed."""
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec

    with open(os.path.join(EXAMPLES, "chaos.json")) as f:
        spec = ChaosSpec.from_dict(json.load(f))
    eng = LifecycleEngine(spec)
    res = eng.run()
    assert res["phase"] == "Succeeded"
    assert res["pods"]["evicted"] > 0  # the n1 failure evicted someone
    assert res["pods"]["unschedulableEvicted"] == []
    assert any(e["type"] == "NodeFail" for e in eng.trace)
    assert any(e["type"] == "NodeRecover" for e in eng.trace)
    # trace JSONL round-trips
    lines = eng.trace_jsonl().splitlines()
    assert [json.loads(x) for x in lines] == eng.trace
