"""Extender proxy: HTTP client, result recording, config URL rewrite, and
the host-callback scheduling loop against a live test extender server
(reference: simulator/scheduler/extender/)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kube_scheduler_simulator_tpu.models import ResourceStore
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration
from kube_scheduler_simulator_tpu.sched.extender import (
    ExtenderService,
    override_extenders_for_simulator,
)
from kube_scheduler_simulator_tpu.server.service import SchedulerService

from helpers import node, pod


class _TestExtender(BaseHTTPRequestHandler):
    """A user extender: filter rejects nodes named in `banned`; prioritize
    gives `favored` score 10 (max) and everyone else 0; preempt vetoes
    candidate nodes named in `preempt_veto` (returns the surviving
    NodeNameToMetaVictims map, the upstream wire)."""

    banned: set = set()
    favored: str = ""
    preempt_veto: set = set()
    calls: list = []

    def log_message(self, *a):
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        args = json.loads(self.rfile.read(length)) if length else {}
        type(self).calls.append((self.path, args))
        if self.path.endswith("/filter"):
            names = args.get("NodeNames") or [
                (n.get("metadata") or {}).get("name")
                for n in (args.get("Nodes") or {}).get("items", [])
            ]
            kept = [n for n in names if n not in self.banned]
            failed = {n: "banned by test extender" for n in names if n in self.banned}
            out = {"NodeNames": kept, "FailedNodes": failed}
        elif self.path.endswith("/prioritize"):
            names = args.get("NodeNames") or [
                (n.get("metadata") or {}).get("name")
                for n in (args.get("Nodes") or {}).get("items", [])
            ]
            out = [
                {"Host": n, "Score": 10 if n == self.favored else 0}
                for n in names
            ]
        elif self.path.endswith("/preempt"):
            # trim the candidate map: vetoed nodes disappear, survivors
            # keep their victims as meta pods (UID-keyed)
            src = args.get("NodeNameToMetaVictims")
            if src is None:
                src = {
                    n: {
                        "Pods": [
                            {"UID": (p.get("metadata") or {}).get("uid")
                             or f"{(p.get('metadata') or {}).get('namespace','default')}/{(p.get('metadata') or {}).get('name')}"}
                            for p in (v or {}).get("Pods") or []
                        ]
                    }
                    for n, v in (args.get("NodeNameToVictims") or {}).items()
                }
            out = {
                "NodeNameToMetaVictims": {
                    n: v for n, v in src.items() if n not in self.preempt_veto
                }
            }
        elif self.path.endswith("/bind"):
            out = {}
        else:
            out = {}
        body = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def extender_server():
    _TestExtender.banned = set()
    _TestExtender.favored = ""
    _TestExtender.preempt_veto = set()
    _TestExtender.calls = []
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _TestExtender)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def extender_config(url, *, node_cache=True, weight=1, preempt=False):
    ext = {
        "urlPrefix": url,
        "filterVerb": "filter",
        "prioritizeVerb": "prioritize",
        "weight": weight,
        "nodeCacheCapable": node_cache,
    }
    if preempt:
        ext["preemptVerb"] = "preempt"
    return SchedulerConfiguration.from_dict(
        {
            "profiles": [{"schedulerName": "default-scheduler"}],
            "extenders": [ext],
        }
    )


class TestExtenderScheduling:
    def test_filter_and_prioritize_shape_placement(self, extender_server):
        _TestExtender.banned = {"n0"}
        _TestExtender.favored = "n2"
        store = ResourceStore()
        for i in range(3):
            store.apply("nodes", node(f"n{i}"))
        store.apply("pods", pod("w"))
        svc = SchedulerService(store, extender_config(extender_server))
        results = svc.schedule()
        assert len(results) == 1
        # n0 banned by extender filter; n2 favored by prioritize
        assert results[0].selected_node == "n2"
        # extender results recorded onto the pod annotations
        got = store.get("pods", "w")
        fr = json.loads(
            got["metadata"]["annotations"][
                "scheduler-simulator/extender-filter-result"
            ]
        )
        assert extender_server in fr
        assert fr[extender_server]["FailedNodes"] == {
            "n0": "banned by test extender"
        }
        pr = json.loads(
            got["metadata"]["annotations"][
                "scheduler-simulator/extender-prioritize-result"
            ]
        )
        # weight 1 x (100/10) rescale: favored host scores 100
        assert {h["Host"]: h["Score"] for h in pr[extender_server]}["n2"] == 100

    def test_all_nodes_banned_is_unschedulable(self, extender_server):
        _TestExtender.banned = {"n0", "n1"}
        store = ResourceStore()
        store.apply("nodes", node("n0"))
        store.apply("nodes", node("n1"))
        store.apply("pods", pod("w"))
        svc = SchedulerService(store, extender_config(extender_server))
        results = svc.schedule()
        assert results[0].status == "Unschedulable"
        assert "nodeName" not in store.get("pods", "w")["spec"]

    def test_non_cache_capable_gets_full_nodes(self, extender_server):
        _TestExtender.favored = "n1"
        store = ResourceStore()
        store.apply("nodes", node("n0"))
        store.apply("nodes", node("n1"))
        store.apply("pods", pod("w"))
        svc = SchedulerService(
            store, extender_config(extender_server, node_cache=False)
        )
        svc.schedule()
        filter_calls = [a for p, a in _TestExtender.calls if p.endswith("/filter")]
        assert filter_calls and "Nodes" in filter_calls[0]
        items = filter_calls[0]["Nodes"]["items"]
        assert {n["metadata"]["name"] for n in items} == {"n0", "n1"}
        assert "status" in items[0]  # full objects, not names

    def test_sequential_state_carries_between_pods(self, extender_server):
        # two big pods: second must land on the other node (bind_fn state)
        store = ResourceStore()
        store.apply("nodes", node("n0", cpu="1"))
        store.apply("nodes", node("n1", cpu="1"))
        store.apply("pods", pod("a", cpu="800m"))
        store.apply("pods", pod("b", cpu="800m"))
        svc = SchedulerService(store, extender_config(extender_server))
        results = svc.schedule()
        sel = {r.pod_name: r.selected_node for r in results}
        assert sorted(sel.values()) == ["n0", "n1"]


class TestExtenderPreemption:
    """Preemption in extender mode (the divergence removed in round 4):
    dry-run nomination → extender preempt verb trims/vetoes candidates →
    evict → retry through the full cycle."""

    def _contended_store(self):
        store = ResourceStore()
        for i in range(2):
            store.apply("nodes", node(f"n{i}", cpu="2", pods="8"))
            store.apply(
                "pods",
                pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}"),
            )
        store.apply("pods", pod("high", cpu="1500m", priority=100))
        return store

    def test_preemption_evicts_and_reschedules(self, extender_server):
        store = self._contended_store()
        svc = SchedulerService(
            store, extender_config(extender_server, preempt=True)
        )
        results = svc.schedule()
        by = {}
        for r in results:
            by.setdefault(r.pod_name, []).append(r)
        assert [r.status for r in by["high"]] == ["Nominated", "Scheduled"]
        nom = by["high"][0]
        assert nom.nominated_node in ("n0", "n1")
        assert len(nom.preemption_victims) == 1
        # the victim was deleted from the store, the preemptor bound
        victim = nom.preemption_victims[0].split("/", 1)[1]
        assert store.get("pods", victim) is None
        assert store.get("pods", "high")["spec"]["nodeName"] == nom.nominated_node
        # the preempt verb transited (and was recorded by) the service
        preempt_calls = [
            a for p, a in _TestExtender.calls if p.endswith("/preempt")
        ]
        assert preempt_calls
        ann = store.get("pods", "high")["metadata"]["annotations"]
        assert "scheduler-simulator/extender-preempt-result" in ann

    def test_extender_veto_steers_nomination(self, extender_server):
        # kernel ranking would pick n0 (lowest index tie-break); the
        # extender vetoes it — n1 must be nominated instead
        _TestExtender.preempt_veto = {"n0"}
        store = self._contended_store()
        svc = SchedulerService(
            store, extender_config(extender_server, preempt=True)
        )
        results = svc.schedule()
        nom = [r for r in results if r.status == "Nominated"][0]
        assert nom.nominated_node == "n1"
        assert store.get("pods", "high")["spec"]["nodeName"] == "n1"
        assert store.get("pods", "low-1") is None
        assert store.get("pods", "low-0") is not None

    def test_extender_full_veto_leaves_unschedulable(self, extender_server):
        _TestExtender.preempt_veto = {"n0", "n1"}
        store = self._contended_store()
        svc = SchedulerService(
            store, extender_config(extender_server, preempt=True)
        )
        results = svc.schedule()
        high = [r for r in results if r.pod_name == "high"]
        assert [r.status for r in high] == ["Unschedulable"]
        # nothing evicted
        assert store.get("pods", "low-0") is not None
        assert store.get("pods", "low-1") is not None

    def test_preemption_full_pod_wire_non_cache_capable(self, extender_server):
        """node_cache=False: the preempt args carry full victim pod
        objects (NodeNameToVictims); the response still maps back through
        meta-victim UIDs."""
        _TestExtender.preempt_veto = {"n0"}
        store = self._contended_store()
        svc = SchedulerService(
            store,
            extender_config(extender_server, node_cache=False, preempt=True),
        )
        results = svc.schedule()
        nom = [r for r in results if r.status == "Nominated"][0]
        assert nom.nominated_node == "n1"
        assert store.get("pods", "high")["spec"]["nodeName"] == "n1"
        # the wire actually carried full pod objects
        pc = [a for p, a in _TestExtender.calls if p.endswith("/preempt")]
        assert pc and "NodeNameToVictims" in pc[0]
        some_victims = next(iter(pc[0]["NodeNameToVictims"].values()))
        assert "metadata" in some_victims["Pods"][0]  # full object

    def test_no_preempt_verb_keeps_kernel_choice(self, extender_server):
        # without a preemptVerb the dry-run's own nomination stands
        store = self._contended_store()
        svc = SchedulerService(
            store, extender_config(extender_server, preempt=False)
        )
        results = svc.schedule()
        high = [r for r in results if r.pod_name == "high"]
        assert [r.status for r in high] == ["Nominated", "Scheduled"]


class TestExtenderServiceUnit:
    def test_unknown_verb_and_id(self, extender_server):
        svc = ExtenderService([{"urlPrefix": extender_server,
                                "filterVerb": "filter"}])
        with pytest.raises(Exception):
            svc.handle("frobnicate", 0, {})
        with pytest.raises(Exception):
            svc.handle("filter", 7, {})

    def test_managed_resources_gating(self):
        from kube_scheduler_simulator_tpu.sched.extender import Extender

        ext = Extender(
            {"urlPrefix": "http://x", "managedResources": [{"name": "foo.com/gpu"}]}
        )
        assert not ext.is_interested(pod("plain"))
        gpu_pod = pod("gpu")
        gpu_pod["spec"]["containers"][0]["resources"]["requests"][
            "foo.com/gpu"
        ] = "1"
        assert ext.is_interested(gpu_pod)

    def test_config_rewrite(self):
        cfg = {
            "extenders": [
                {
                    "urlPrefix": "https://user.example/sched",
                    "filterVerb": "filter",
                    "bindVerb": "bind",
                    "enableHTTPS": True,
                    "tlsConfig": {"insecure": True},
                },
                {"urlPrefix": "http://other/", "prioritizeVerb": "rank"},
            ]
        }
        out = override_extenders_for_simulator(cfg, 1212)
        e0, e1 = out["extenders"]
        assert e0["urlPrefix"] == "http://localhost:1212/api/v1/extender/"
        assert e0["filterVerb"] == "filter/0"
        assert e0["bindVerb"] == "bind/0"
        assert e0["enableHTTPS"] is False and "tlsConfig" not in e0
        assert e1["prioritizeVerb"] == "prioritize/1"
        assert "filterVerb" not in e1


class TestExtenderProxyRoute:
    def test_proxy_forwards_and_records(self, extender_server):
        import urllib.request

        from kube_scheduler_simulator_tpu.server import (
            SimulatorServer,
            SimulatorService,
        )

        _TestExtender.banned = {"nope"}
        sim = SimulatorService(extender_config(extender_server))
        srv = SimulatorServer(sim, port=0).start()
        try:
            args = {
                "Pod": pod("w"),
                "NodeNames": ["ok", "nope"],
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/api/v1/extender/filter/0",
                data=json.dumps(args).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert out["NodeNames"] == ["ok"]
            ann = sim.scheduler.extender_service.annotations_for("default", "w")
            assert "scheduler-simulator/extender-filter-result" in ann
        finally:
            srv.shutdown()
