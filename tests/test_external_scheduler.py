"""External-scheduler mode (EXTERNAL_SCHEDULER_ENABLED): the simulator
serves store/CRUD/watch/export with the internal engine disabled, and an
external scheduler binds pods through the CRUD surface (reference
config.go:34-35 + :115-121, simulator.go:75-80, scheduler.go:55-61)."""

import json
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.server import config as envconfig
from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import (
    SchedulerServiceDisabled,
    SimulatorService,
)

from helpers import node, pod


def _req(url, data=None, method="GET"):
    req = urllib.request.Request(
        url,
        data=None if data is None else json.dumps(data).encode(),
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        body = resp.read()
        return resp.status, json.loads(body) if body else None


def _status_of(err_call):
    try:
        err_call()
    except urllib.error.HTTPError as e:
        return e.code
    return None


class TestEnvFlag:
    def test_parse_bool_semantics(self):
        for raw, want in [("true", True), ("1", True), ("T", True),
                          ("false", False), ("0", False), ("F", False)]:
            cfg = envconfig.from_env({"EXTERNAL_SCHEDULER_ENABLED": raw})
            assert cfg.external_scheduler_enabled is want
        assert not envconfig.from_env({}).external_scheduler_enabled
        with pytest.raises(ValueError):
            envconfig.from_env({"EXTERNAL_SCHEDULER_ENABLED": "yes-please"})


class TestDisabledService:
    def test_scheduler_calls_refused(self):
        svc = SimulatorService(external_scheduler_enabled=True)
        with pytest.raises(SchedulerServiceDisabled):
            svc.scheduler.get_config()
        with pytest.raises(SchedulerServiceDisabled):
            svc.scheduler.restart({"profiles": []})
        with pytest.raises(SchedulerServiceDisabled):
            svc.scheduler.schedule()
        with pytest.raises(SchedulerServiceDisabled):
            svc.scheduler.schedule_gang()

    def test_export_omits_config_and_import_skips_restart(self):
        svc = SimulatorService(external_scheduler_enabled=True)
        snap = svc.export()
        assert snap["schedulerConfig"] is None
        # importing a snapshot that carries a config must not blow up —
        # the restart is skipped, resources still land (export.go:251-257)
        snap2 = {
            "pods": [],
            "nodes": [node("n-ext")],
            "schedulerConfig": {"profiles": []},
        }
        errs = svc.import_(snap2, ignore_err=True)
        assert errs == []
        assert svc.store.get("nodes", "n-ext") is not None

    def test_reset_tolerated(self):
        svc = SimulatorService(external_scheduler_enabled=True)
        svc.reset()  # must not raise

    def test_imported_bound_pods_not_counted_as_external_passes(self):
        """Replicating a cluster whose pods are already bound must not
        masquerade as external scheduler activity — only the
        pending→bound transition counts."""
        svc = SimulatorService(external_scheduler_enabled=True)
        svc.import_(
            {
                "nodes": [node("n0")],
                "pods": [pod("prebound", node_name="n0"), pod("waiting")],
            },
            ignore_err=True,
        )
        assert svc.scheduler.metrics.snapshot()["passes"] == 0
        # a real external bind of the pending pod DOES count
        bound = svc.store.get("pods", "waiting")
        bound["spec"]["nodeName"] = "n0"
        svc.store.apply("pods", bound)
        assert svc.scheduler.metrics.snapshot()["passes"] == 1


class TestExternalSchedulerOverHTTP:
    """Drive a fake external scheduler against the serving surface."""

    def setup_method(self):
        self.server = SimulatorServer(
            SimulatorService(external_scheduler_enabled=True), port=0
        ).start()
        self.base = f"http://127.0.0.1:{self.server.port}/api/v1"

    def teardown_method(self):
        self.server.shutdown()

    def test_full_external_flow(self):
        base = self.base
        # config and scheduling surfaces answer 400 (schedulerconfig.go:32)
        assert _status_of(lambda: _req(f"{base}/schedulerconfiguration")) == 400
        assert (
            _status_of(
                lambda: _req(f"{base}/schedule", data={}, method="POST")
            )
            == 400
        )
        assert (
            _status_of(
                lambda: _req(
                    f"{base}/schedulerconfiguration",
                    data={"profiles": []},
                    method="POST",
                )
            )
            == 400
        )
        # the cluster surface still works: seed a node + a pending pod
        _req(f"{base}/resources/nodes", data=node("n0"), method="POST")
        _req(f"{base}/resources/pods", data=pod("p0"), method="POST")
        st, listing = _req(f"{base}/resources/pods")
        pending = [
            o
            for o in listing["items"]
            if not (o.get("spec", {}) or {}).get("nodeName")
        ]
        assert [o["metadata"]["name"] for o in pending] == ["p0"]
        # the external scheduler binds through CRUD: set spec.nodeName
        bound = pending[0]
        bound["spec"]["nodeName"] = "n0"
        st, _ = _req(f"{base}/resources/pods", data=bound, method="PUT")
        assert st == 201
        st, got = _req(f"{base}/resources/pods/default/p0")
        assert got["spec"]["nodeName"] == "n0"
        # ... and the bind was recorded as an external pass
        st, snap = _req(f"{base}/metrics")
        assert snap["passes"] == 1
        assert snap["recent"][0]["mode"] == "external"
        assert snap["totalScheduled"] == 1
        # re-applying the bound pod must not double-count
        _req(f"{base}/resources/pods", data=bound, method="PUT")
        st, snap = _req(f"{base}/metrics")
        assert snap["passes"] == 1
        # export serves resources without a schedulerConfig
        st, exported = _req(f"{base}/export")
        assert exported["schedulerConfig"] is None
        assert len(exported["nodes"]) == 1
        # reset still answers 202 (reset.go:80 tolerates disabled)
        req = urllib.request.Request(f"{base}/reset", data=b"", method="PUT")
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 202
