"""The engine-internal fault plane (utils/faultinject.py): grammar,
determinism, and the env-driven activation cache."""

from __future__ import annotations

import time

import pytest

from kube_scheduler_simulator_tpu.utils import faultinject
from kube_scheduler_simulator_tpu.utils.faultinject import (
    FaultPlane,
    InjectedFault,
)


class TestGrammar:
    def test_probability_and_duration_sites(self):
        plane = FaultPlane.parse("compile_fail:0.3,compile_slow:250ms")
        assert plane.rules == {"compile_fail": 0.3, "compile_slow": 0.25}

    def test_seconds_and_millis(self):
        assert FaultPlane.parse("compile_slow:5s").rules["compile_slow"] == 5.0
        assert FaultPlane.parse("compile_slow:50ms").rules["compile_slow"] == 0.05

    def test_whitespace_and_empty_entries_tolerated(self):
        plane = FaultPlane.parse(" compile_fail : 1.0 , ,device_error:0.5,")
        assert plane.rules == {"compile_fail": 1.0, "device_error": 0.5}

    def test_execution_ladder_sites(self):
        """The ISSUE 9 grammar additions: device_lost is a probability
        site, dispatch_hang a duration site."""
        plane = FaultPlane.parse("device_lost:1.0,dispatch_hang:50ms")
        assert plane.rules == {"device_lost": 1.0, "dispatch_hang": 0.05}
        with pytest.raises(InjectedFault) as exc:
            plane.maybe_raise("device_lost")
        assert exc.value.site == "device_lost"
        with pytest.raises(ValueError):
            FaultPlane.parse("device_lost:2.0")  # probability bounds hold
        with pytest.raises(ValueError):
            FaultPlane.parse("dispatch_hang:0.5")  # durations need a unit

    def test_fleet_network_sites(self):
        """The fleet chaos grammar (docs/resilience.md): net_drop,
        net_partition, and worker_kill are probability sites fired in
        the router's network chokepoint; net_delay is a duration."""
        plane = FaultPlane.parse(
            "net_drop:1.0,net_partition:0.5,worker_kill:0.1,net_delay:20ms"
        )
        assert plane.rules == {
            "net_drop": 1.0,
            "net_partition": 0.5,
            "worker_kill": 0.1,
            "net_delay": 0.02,
        }
        with pytest.raises(InjectedFault) as exc:
            plane.maybe_raise("net_drop")
        assert exc.value.site == "net_drop"
        with pytest.raises(ValueError):
            FaultPlane.parse("net_drop:1.5")  # probability bounds hold
        with pytest.raises(ValueError):
            FaultPlane.parse("net_delay:0.5")  # durations need a unit

    @pytest.mark.parametrize(
        "bad",
        [
            "nonsense:0.5",  # unknown site
            "compile_fail",  # no value
            "compile_fail:maybe",  # not a number
            "compile_fail:1.5",  # probability outside [0, 1]
            "compile_slow:5",  # duration without unit
            "compile_slow:-1s",  # negative duration
        ],
    )
    def test_strict_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            FaultPlane.parse(bad)


class TestDraws:
    def test_probability_one_always_raises_and_counts(self):
        plane = FaultPlane.parse("compile_fail:1.0")
        for _ in range(3):
            with pytest.raises(InjectedFault) as exc:
                plane.maybe_raise("compile_fail")
            assert exc.value.site == "compile_fail"
        assert plane.counts() == {"compile_fail": 3}

    def test_probability_zero_never_raises(self):
        plane = FaultPlane.parse("compile_fail:0.0")
        for _ in range(50):
            plane.maybe_raise("compile_fail")
        assert plane.counts() == {}

    def test_unconfigured_site_is_silent(self):
        plane = FaultPlane.parse("compile_fail:1.0")
        plane.maybe_raise("device_error")  # not in the spec: no fault

    def test_seeded_draws_are_deterministic(self):
        def outcomes(seed):
            plane = FaultPlane.parse("device_error:0.5", seed=seed)
            out = []
            for _ in range(32):
                try:
                    plane.maybe_raise("device_error")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        assert outcomes(7) == outcomes(7)
        assert outcomes(7) != outcomes(8)  # different stream

    def test_sites_draw_independent_streams(self):
        """Adding a site never reshuffles another's draws."""

        def device_outcomes(spec):
            plane = FaultPlane.parse(spec, seed=3)
            out = []
            for _ in range(16):
                try:
                    plane.maybe_raise("device_error")
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        assert device_outcomes("device_error:0.5") == device_outcomes(
            "device_error:0.5,compile_fail:0.5"
        )

    def test_delay_sleeps_and_counts(self):
        plane = FaultPlane.parse("compile_slow:30ms")
        t0 = time.perf_counter()
        slept = plane.delay("compile_slow")
        assert slept == pytest.approx(0.03)
        assert time.perf_counter() - t0 >= 0.025
        assert plane.counts() == {"compile_slow": 1}
        assert plane.delay("compile_fail") == 0.0  # unconfigured: no sleep


class TestActivePlane:
    def test_env_activation_and_cache_invalidation(self, monkeypatch):
        monkeypatch.delenv(faultinject.ENV_VAR, raising=False)
        assert faultinject.active() is None
        monkeypatch.setenv(faultinject.ENV_VAR, "compile_fail:1.0")
        plane = faultinject.active()
        assert plane is not None and plane.rules == {"compile_fail": 1.0}
        # same env string: the SAME parsed plane (stream state persists)
        assert faultinject.active() is plane
        monkeypatch.setenv(faultinject.ENV_VAR, "device_error:0.5")
        assert faultinject.active().rules == {"device_error": 0.5}
        monkeypatch.setenv(faultinject.ENV_VAR, "")
        assert faultinject.active() is None

    def test_seed_env_feeds_streams(self, monkeypatch):
        monkeypatch.setenv(faultinject.ENV_VAR, "device_error:0.5")
        monkeypatch.setenv(faultinject.SEED_VAR, "17")
        assert faultinject.active().seed == 17

    def test_malformed_env_raises_at_fire_point(self, monkeypatch):
        monkeypatch.setenv(faultinject.ENV_VAR, "compile_fail:bogus")
        with pytest.raises(ValueError):
            faultinject.active()

    def test_activate_overrides_env(self, monkeypatch):
        monkeypatch.setenv(faultinject.ENV_VAR, "compile_fail:1.0")
        try:
            faultinject.activate(None)
            assert faultinject.active() is None
            plane = FaultPlane.parse("worker_crash:1.0")
            faultinject.activate(plane)
            assert faultinject.active() is plane
        finally:
            faultinject.deactivate()
        assert faultinject.active().rules == {"compile_fail": 1.0}
