"""Vmapped failure sweeps (lifecycle/faultsweep.py): the vmap/sequential
parity contract, failure-mask semantics (no placement on failed nodes,
eviction accounting), and seeded mask determinism."""

import numpy as np
import pytest

from kube_scheduler_simulator_tpu.lifecycle.faultsweep import FaultSweep

from helpers import node, pod
from test_engine_parity import restricted_config


def _cfg():
    """A restricted config keeps the sweep's compiled program small."""
    return restricted_config()


def _sweep(n_nodes=4, bound=10, pending=2, cpu="8"):
    nodes = [node(f"n{i}", cpu=cpu) for i in range(n_nodes)]
    pods = [
        pod(f"b{i}", cpu="1", node_name=f"n{i % n_nodes}") for i in range(bound)
    ] + [pod(f"q{i}", cpu="1") for i in range(pending)]
    return FaultSweep.from_cluster(nodes, pods, _cfg())


class TestFaultSweep:
    def test_vmapped_matches_sequential(self):
        sweep = _sweep()
        masks = sweep.sample_masks(8, seed=42, fail_prob=0.3)
        profile = sweep.run(masks)
        assert profile["scenarios"] == 8
        for s in range(8):
            a, ev, re, st, rounds = sweep.run_one(np.asarray(masks)[s])
            assert np.array_equal(np.asarray(a), profile["assignments"][s]), s
            assert int(ev) == profile["evicted"][s], s
            assert int(re) == profile["rescheduled"][s], s
            assert int(st) == profile["stranded"][s], s

    def test_failed_nodes_take_no_pods_and_eviction_counts(self):
        sweep = _sweep()
        masks = np.asarray(sweep.sample_masks(8, seed=7, fail_prob=0.4))
        profile = sweep.run(masks)
        baseline = np.asarray(sweep._state_bound.assignment)
        for s in range(8):
            failed = np.nonzero(masks[s])[0]
            a = profile["assignments"][s]
            placed = a[a >= 0]
            assert not np.isin(placed, failed).any(), s
            # evicted == baseline-bound pods whose node failed
            expect = int(np.isin(baseline[baseline >= 0], failed).sum())
            assert profile["evicted"][s] == expect, s
            assert (
                profile["rescheduled"][s] + profile["stranded"][s]
                == profile["evicted"][s]
            ), s

    def test_no_failures_is_a_no_op_for_bound_pods(self):
        sweep = _sweep()
        masks = sweep.sample_masks(2, seed=1, fail_prob=0.0)
        profile = sweep.run(masks)
        assert profile["totals"]["evicted"] == 0
        baseline = np.asarray(sweep._state_bound.assignment)
        for s in range(2):
            a = profile["assignments"][s]
            keep = baseline >= 0
            assert np.array_equal(a[keep], baseline[keep]), s
            # the two pending queue pods placed too
            assert (a >= 0).sum() >= keep.sum()

    def test_stranded_when_capacity_lost(self):
        # 2 nodes exactly full; failing n1 leaves nowhere to go
        nodes = [node("n0", cpu="2"), node("n1", cpu="2")]
        pods = [
            pod("a0", cpu="2", node_name="n0"),
            pod("a1", cpu="2", node_name="n1"),
        ]
        sweep = FaultSweep.from_cluster(nodes, pods, _cfg())
        mask = np.zeros((1, sweep.enc.N), bool)
        mask[0, sweep.enc.node_names.index("n1")] = True
        profile = sweep.run(mask)
        assert profile["evicted"] == [1]
        assert profile["stranded"] == [1]
        assert profile["worstScenario"] == 0
        # the evicted pod is unplaced in the decode
        (placements,) = sweep.placements(profile["assignments"])
        assert placements[("default", "a1")] == ""
        assert placements[("default", "a0")] == "n0"

    def test_masks_deterministic_and_validated(self):
        sweep = _sweep(n_nodes=3, bound=3, pending=0)
        m1 = np.asarray(sweep.sample_masks(16, seed=5, fail_prob=0.5))
        m2 = np.asarray(sweep.sample_masks(16, seed=5, fail_prob=0.5))
        assert np.array_equal(m1, m2)
        assert m1.shape == (16, sweep.enc.N)
        # only REAL nodes fail (padding, if any, stays False)
        assert not m1[:, 3:].any()
        with pytest.raises(ValueError, match="fail_prob"):
            sweep.sample_masks(4, seed=0, fail_prob=1.5)
        with pytest.raises(ValueError, match="n_scenarios"):
            sweep.sample_masks(0, seed=0, fail_prob=0.5)
        with pytest.raises(ValueError, match="masks must be"):
            sweep.run(np.zeros((2, sweep.enc.N + 1), bool))

    def test_unknown_baseline_node_rejected(self):
        nodes = [node("n0")]
        pods = [pod("a0", cpu="1", node_name="ghost")]
        with pytest.raises(ValueError, match="unknown node"):
            FaultSweep.from_cluster(nodes, pods, _cfg())

    def test_mesh_shards_scenario_axis_over_replicas(self):
        # the scenario axis is the Monte-Carlo axis: sharded over
        # 'replicas' like parallel/sweep.py's variant axis, results
        # identical to the unsharded run
        from kube_scheduler_simulator_tpu.parallel.mesh import build_mesh

        mesh = build_mesh(4, replicas=4, node_shards=1)
        nodes = [node(f"n{i}", cpu="8") for i in range(4)]
        pods = [
            pod(f"b{i}", cpu="1", node_name=f"n{i % 4}") for i in range(8)
        ]
        plain = FaultSweep.from_cluster(nodes, pods, _cfg())
        sharded = FaultSweep.from_cluster(nodes, pods, _cfg(), mesh=mesh)
        masks = plain.sample_masks(8, seed=9, fail_prob=0.3)
        p1 = plain.run(masks)
        p2 = sharded.run(masks)
        assert np.array_equal(p1["assignments"], p2["assignments"])
        assert p1["totals"] == p2["totals"]
        with pytest.raises(ValueError, match="replicas"):
            sharded.run(np.asarray(masks)[:6])  # 6 % 4 != 0
