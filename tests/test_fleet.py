"""The horizontal serving fleet (fleet/router.py, docs/fleet.md):
session-affine routing over adopted in-process workers, placement
parity with the single-process server, structured health bodies, the
`worker` exposition label, federated scrapes, 503 passthrough, re-home
on worker death, and the rolling restart — all against in-process
`SimulatorServer` workers (no subprocess boots; tools/fleet_smoke.py
exercises the spawned-worker path)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.fleet import FleetRouter
from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService
from kube_scheduler_simulator_tpu.utils.metrics import parse_prometheus_text

from helpers import node, pod


def _req(port, method, path, body=None, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None, dict(resp.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None, dict(e.headers)


def _raw(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=300
    ) as resp:
        return resp.read()


@pytest.fixture()
def fleet(tmp_path):
    """Two in-process workers adopted by a router. Probe interval is
    effectively off (60s): death-detection tests drive probe_once()
    deterministically by hand."""
    servers, dirs = [], []
    for i in range(2):
        d = str(tmp_path / f"w{i}")
        srv = SimulatorServer(
            SimulatorService(),
            port=0,
            session_config={"snapshot_dir": d},
        ).start()
        servers.append(srv)
        dirs.append(d)
    router = FleetRouter(
        adopt=[
            (f"http://127.0.0.1:{srv.port}", d)
            for srv, d in zip(servers, dirs)
        ],
        port=0,
        probe_interval_s=60.0,
        fleet_dir=str(tmp_path / "fleet"),
    ).start()
    yield router, servers
    router.shutdown(drain=False)
    for srv in servers:
        try:
            srv.shutdown()
        except Exception:
            pass


def _owner_server(router, servers, sid):
    w = router.worker_for(sid)
    idx = int(w.id[1:])  # adopted ids are w0..wN in adoption order
    return w, servers[idx]


class TestFleetRouting:
    def test_fleet_doc_shows_ready_ring(self, fleet):
        router, _ = fleet
        code, doc, _ = _req(router.port, "GET", "/api/v1/fleet")
        assert code == 200
        assert [w["id"] for w in doc["workers"]] == ["w0", "w1"]
        assert all(w["state"] == "ready" for w in doc["workers"])
        assert doc["ring"]["workers"] == ["w0", "w1"]
        assert doc["roll"]["rolling"] is False
        # "default" is pre-placed on its ring owner
        assert doc["sessions"]["default"] in ("w0", "w1")

    def test_create_pins_ring_owner_and_requests_stick(self, fleet):
        router, servers = fleet
        code, doc, _ = _req(
            router.port, "POST", "/api/v1/sessions", {"id": "aff-1"}
        )
        assert code == 201 and doc["id"] == "aff-1"
        _, fdoc, _ = _req(router.port, "GET", "/api/v1/fleet")
        owner_wid = fdoc["sessions"]["aff-1"]
        w, owner_srv = _owner_server(router, servers, "aff-1")
        assert w.id == owner_wid
        # the session exists ONLY on the owner worker
        for i, srv in enumerate(servers):
            _, sdoc, _ = _req(srv.port, "GET", "/api/v1/sessions")
            ids = {s["id"] for s in sdoc["sessions"]}
            assert ("aff-1" in ids) == (f"w{i}" == owner_wid)
        # scoped requests through the router land there and work
        base = "/api/v1/sessions/aff-1"
        _req(router.port, "PUT", f"{base}/resources/nodes", node("n0"))
        _req(router.port, "PUT", f"{base}/resources/pods", pod("p0"))
        code, out, _ = _req(router.port, "POST", f"{base}/schedule")
        assert code == 200 and out["scheduled"] == 1
        # DELETE through the router evicts the placement record
        assert _req(router.port, "DELETE", base)[0] == 200
        _, fdoc, _ = _req(router.port, "GET", "/api/v1/fleet")
        assert "aff-1" not in fdoc["sessions"]

    def test_minted_id_is_routable(self, fleet):
        router, _ = fleet
        code, doc, _ = _req(router.port, "POST", "/api/v1/sessions", {})
        assert code == 201
        sid = doc["id"]
        code, info, _ = _req(router.port, "GET", f"/api/v1/sessions/{sid}")
        assert code == 200 and info["id"] == sid

    def test_bad_explicit_ids_are_rejected(self, fleet):
        router, _ = fleet
        for bad in ("bad id!", "default", "x" * 65):
            code, _, _ = _req(
                router.port, "POST", "/api/v1/sessions", {"id": bad}
            )
            assert code == 400, bad
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "dup-1"})[0]
            == 201
        )
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "dup-1"})[0]
            == 400
        )

    def test_legacy_surface_rides_the_default_owner(self, fleet):
        router, servers = fleet
        _req(router.port, "PUT", "/api/v1/resources/nodes", node("ln0"))
        _req(router.port, "PUT", "/api/v1/resources/pods", pod("lp0"))
        code, out, _ = _req(router.port, "POST", "/api/v1/schedule")
        assert code == 200 and out["scheduled"] == 1
        # the write landed on the default session's owner, nowhere else
        _, owner_srv = _owner_server(router, servers, "default")
        code, items, _ = _req(
            owner_srv.port, "GET", "/api/v1/resources/pods"
        )
        assert {p["metadata"]["name"] for p in items["items"]} == {"lp0"}


class TestPlacementParity:
    def test_fleet_worker_placements_byte_identical_to_single_process(
        self, fleet, tmp_path
    ):
        """The same op sequence against a fleet-routed session and a
        bare single-process server must bind the same pods to the same
        nodes with byte-identical resource documents — fleet membership
        must not perturb scheduling."""
        router, _ = fleet
        solo = SimulatorServer(
            SimulatorService(),
            port=0,
            session_config={"snapshot_dir": str(tmp_path / "solo")},
        ).start()
        try:
            def drive(port):
                assert (
                    _req(port, "POST", "/api/v1/sessions", {"id": "parity-1"})[0]
                    == 201
                )
                base = "/api/v1/sessions/parity-1"
                for i in range(3):
                    _req(
                        port,
                        "PUT",
                        f"{base}/resources/nodes",
                        node(f"n{i}", cpu="2", mem="4Gi"),
                    )
                for i in range(6):
                    _req(
                        port,
                        "PUT",
                        f"{base}/resources/pods",
                        pod(f"p{i}", cpu="500m", mem="512Mi"),
                    )
                code, out, _ = _req(port, "POST", f"{base}/schedule")
                assert code == 200 and out["scheduled"] == 6
                return _raw(port, f"{base}/resources/pods")

            via_fleet = drive(router.port)
            via_solo = drive(solo.port)
        finally:
            solo.shutdown()
        assert via_fleet == via_solo


class TestHealthBodies:
    def test_worker_healthz_is_structured(self, fleet):
        _, servers = fleet
        code, doc, _ = _req(servers[0].port, "GET", "/api/v1/healthz")
        assert code == 200 and doc["ok"] is True
        assert doc["workerId"] is None  # no KSS_WORKER_ID in the suite
        assert doc["uptimeSeconds"] >= 0
        assert doc["draining"] is False
        assert isinstance(doc["activeSessions"], int)

    def test_worker_readyz_is_structured(self, fleet):
        _, servers = fleet
        code, doc, _ = _req(servers[0].port, "GET", "/api/v1/readyz")
        assert code == 200
        assert doc["draining"] is False
        assert "uptimeSeconds" in doc and "activeSessions" in doc

    def test_router_healthz_readyz(self, fleet):
        router, _ = fleet
        code, doc, _ = _req(router.port, "GET", "/api/v1/healthz")
        assert code == 200 and doc["router"] is True
        assert doc["workers"] == {"w0": "ready", "w1": "ready"}
        code, doc, _ = _req(router.port, "GET", "/api/v1/readyz")
        assert code == 200 and doc["ready"] is True
        assert doc["readyWorkers"] == ["w0", "w1"]


class TestWorkerLabel:
    def test_worker_id_labels_every_sample_and_json(
        self, fleet, monkeypatch
    ):
        _, servers = fleet
        monkeypatch.setenv("KSS_WORKER_ID", "wx")
        raw = _raw(
            servers[0].port, "/api/v1/metrics?format=prometheus"
        ).decode()
        families = parse_prometheus_text(raw)
        assert families
        for fam in families.values():
            for _name, labels, _value in fam["samples"]:
                assert labels.get("worker") == "wx"
        code, doc, _ = _req(servers[0].port, "GET", "/api/v1/metrics")
        assert code == 200 and doc["workerId"] == "wx"

    def test_without_worker_id_exposition_is_unlabeled(self, fleet):
        _, servers = fleet
        raw = _raw(
            servers[0].port, "/api/v1/metrics?format=prometheus"
        ).decode()
        assert 'worker="' not in raw
        code, doc, _ = _req(servers[0].port, "GET", "/api/v1/metrics")
        assert code == 200 and "workerId" not in doc


class TestFederation:
    def test_federated_metrics_json(self, fleet):
        router, _ = fleet
        code, doc, _ = _req(router.port, "GET", "/api/v1/metrics")
        assert code == 200 and doc["fleet"] is True
        assert doc["workersTotal"] == 2 and doc["workersReady"] == 2
        assert set(doc["workers"]) == {"w0", "w1"}
        for wdoc in doc["workers"].values():
            assert "passes" in wdoc

    def test_aggregate_counts_named_session_passes(self, fleet):
        # the worker-level /metrics doc only sees the default session;
        # the fleet aggregate must count NAMED sessions' passes too
        router, _ = fleet
        _req(router.port, "POST", "/api/v1/sessions", {"id": "agg-1"})
        base = "/api/v1/sessions/agg-1"
        _req(router.port, "PUT", f"{base}/resources/nodes", node("n0"))
        _req(router.port, "PUT", f"{base}/resources/pods", pod("p0"))
        code, out, _ = _req(router.port, "POST", f"{base}/schedule")
        assert code == 200 and out["scheduled"] == 1
        _, doc, _ = _req(router.port, "GET", "/api/v1/metrics")
        assert doc["aggregate"]["passes"] >= 1
        assert doc["aggregate"]["totalScheduled"] >= 1

    def test_federated_prometheus_merges_and_labels(self, fleet):
        router, _ = fleet
        raw = _raw(router.port, "/api/v1/metrics?format=prometheus").decode()
        families = parse_prometheus_text(raw)  # strict: merge must hold
        assert families["kss_fleet_workers"]["samples"][0][2] == 2.0
        assert families["kss_fleet_workers_ready"]["samples"][0][2] == 2.0
        seen = {
            labels.get("worker")
            for name, fam in families.items()
            if not name.startswith("kss_fleet_")
            for _n, labels, _v in fam["samples"]
        }
        # adopted workers self-label nothing; the router injected ids
        assert seen == {"w0", "w1"}

    def test_federated_alerts_and_timeseries(self, fleet):
        router, _ = fleet
        code, doc, _ = _req(router.port, "GET", "/api/v1/alerts")
        assert code == 200 and doc["fleet"] is True
        assert isinstance(doc["active"], list)
        code, doc, _ = _req(router.port, "GET", "/api/v1/timeseries")
        assert code == 200 and doc["fleet"] is True
        assert set(doc["workers"]) == {"w0", "w1"}

    def test_merged_sessions_tag_workers(self, fleet):
        router, _ = fleet
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "fed-1"})[0]
            == 201
        )
        code, doc, _ = _req(router.port, "GET", "/api/v1/sessions")
        assert code == 200
        by_id = {s["id"]: s for s in doc["sessions"]}
        assert by_id["fed-1"]["worker"] in ("w0", "w1")
        # each worker contributes its own default session
        defaults = [s for s in doc["sessions"] if s["id"] == "default"]
        assert {s["worker"] for s in defaults} == {"w0", "w1"}


class TestDegradation:
    def test_worker_503_passes_through_with_retry_after(self, fleet):
        router, servers = fleet
        for srv in servers:
            srv.sessions.max_sessions = 1  # default occupies the slot
        code, doc, headers = _req(
            router.port, "POST", "/api/v1/sessions", {"id": "full-1"}
        )
        assert code == 503
        assert headers.get("Retry-After")
        assert doc["kind"] != "WorkerUnavailable"  # the WORKER shed it

    def test_unroutable_session_is_shed_with_retry_after(self, fleet):
        router, servers = fleet
        for srv in servers:
            srv.shutdown()
        for _ in range(3):
            router.probe_once()
        code, doc, headers = _req(
            router.port, "GET", "/api/v1/sessions/nope-1"
        )
        assert code == 503
        assert doc["kind"] == "WorkerUnavailable"
        assert headers.get("Retry-After")
        _, fdoc, _ = _req(router.port, "GET", "/api/v1/fleet")
        assert fdoc["shedRequests"] >= 1
        # the router itself also reports not-ready now
        code, rdoc, _ = _req(router.port, "GET", "/api/v1/readyz")
        assert code == 503 and rdoc["ready"] is False


class TestRehomeOnDeath:
    def test_dead_workers_sessions_move_to_ring_successor(self, fleet):
        router, servers = fleet
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "home-1"})[0]
            == 201
        )
        base = "/api/v1/sessions/home-1"
        _req(router.port, "PUT", f"{base}/resources/nodes", node("hn0"))
        _req(router.port, "PUT", f"{base}/resources/pods", pod("hp0"))
        # checkpoint the session (the drain path does this on SIGTERM;
        # in-process workers have no signal handler, so evict by hand)
        assert _req(router.port, "POST", f"{base}/evict")[0] == 200
        owner, owner_srv = _owner_server(router, servers, "home-1")
        owner_srv.shutdown()  # the worker dies without warning
        for _ in range(3):
            router.probe_once()  # 3 failed probes => dead + re-home
        _, fdoc, _ = _req(router.port, "GET", "/api/v1/fleet")
        states = {w["id"]: w["state"] for w in fdoc["workers"]}
        assert states[owner.id] == "dead"
        successor = fdoc["sessions"]["home-1"]
        assert successor != owner.id
        assert fdoc["rehomedSessions"] >= 1
        # the session answers from the successor with its state intact
        code, items, _ = _req(
            router.port, "GET", f"{base}/resources/pods"
        )
        assert code == 200
        assert {p["metadata"]["name"] for p in items["items"]} == {"hp0"}


class TestRoll:
    def test_roll_drains_rehomes_and_reports(self, fleet):
        router, servers = fleet
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "roll-1"})[0]
            == 201
        )
        base = "/api/v1/sessions/roll-1"
        _req(router.port, "PUT", f"{base}/resources/pods", pod("rp0"))
        code, doc, _ = _req(router.port, "POST", "/api/v1/fleet/roll")
        assert code == 202 and doc["started"] is True
        # a second roll while one runs is refused
        code, doc, _ = _req(router.port, "POST", "/api/v1/fleet/roll")
        assert code == 202 and doc["started"] is False
        deadline = 30.0
        import time as _time

        end = _time.monotonic() + deadline
        while _time.monotonic() < end:
            _, fdoc, _ = _req(router.port, "GET", "/api/v1/fleet")
            if not fdoc["roll"]["rolling"]:
                break
            _time.sleep(0.1)
        assert fdoc["roll"]["rolling"] is False
        assert fdoc["roll"]["rolled"] == ["w0", "w1"]
        # adopted members cannot be restarted by the router: the roll
        # drained them (sessions snapshotted) and left them out of the
        # ring for their embedding owner to bring back
        states = {w["id"]: w["state"] for w in fdoc["workers"]}
        assert states == {"w0": "dead", "w1": "dead"}
        # w0 rolled first, so its sessions re-homed to w1 before w1's
        # turn; at minimum the default session moved
        assert fdoc["roll"]["rehomedSessions"] >= 1
