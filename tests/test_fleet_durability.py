"""The fleet durability plane + router resilience (docs/fleet.md,
docs/resilience.md): digest-guarded checkpoint transport, write-ahead
journal replay, ring successor placement, the per-worker circuit
breaker's state machine, and crash-kill re-home parity against an
uninterrupted single-process oracle — all over in-process
`SimulatorServer` workers (tools/fleet_chaos_smoke.py exercises the
spawned-worker + kill -9 path)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.fleet import FleetRouter
from kube_scheduler_simulator_tpu.fleet.ring import HashRing
from kube_scheduler_simulator_tpu.lifecycle.checkpoint import canonical_bytes
from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService
from kube_scheduler_simulator_tpu.server import durability

from helpers import node, pod


def _req(port, method, path, body=None, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None, dict(resp.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None, dict(e.headers)


def _raw(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=300
    ) as resp:
        return resp.read()


@pytest.fixture()
def durable_fleet(tmp_path, monkeypatch):
    """Three journaling in-process workers adopted by a router forced
    onto the HTTP checkpoint transport (the cross-host behavior — the
    same-filesystem file move would mask transport bugs). Probes are
    driven by hand; replication ships by hand (`ship_once`) so tests
    never wait on the ticker."""
    monkeypatch.setenv("KSS_FLEET_TRANSPORT", "http")
    monkeypatch.setenv("KSS_FLEET_RETRY_BACKOFF_S", "0.01")
    servers, dirs = [], []
    for i in range(3):
        d = str(tmp_path / f"w{i}")
        srv = SimulatorServer(
            SimulatorService(),
            port=0,
            session_config={"snapshot_dir": d, "journal": True},
        ).start()
        servers.append(srv)
        dirs.append(d)
    router = FleetRouter(
        adopt=[
            (f"http://127.0.0.1:{srv.port}", d)
            for srv, d in zip(servers, dirs)
        ],
        port=0,
        probe_interval_s=60.0,
        fleet_dir=str(tmp_path / "fleet"),
    ).start()
    yield router, servers
    router.shutdown(drain=False)
    for srv in servers:
        try:
            srv.shutdown()
        except Exception:
            pass


def _owner_idx(router, sid):
    w = router.worker_for(sid)
    return int(w.id[1:])  # adopted ids are w0..wN in adoption order


class TestTransportUnits:
    """The digest-guarded unit (server/durability.py): any torn or
    tampered transfer is named, not adopted."""

    def test_corrupted_payload_is_rejected(self):
        doc = {"format": "kss-session-checkpoint/v1", "session": {"a": 1}}
        unit = durability.build_unit("s-1", doc, [{"rv": 1, "t": "put"}])
        # intact round-trips
        got_doc, got_entries = durability.verify_unit(unit)
        assert got_doc == doc and got_entries == [{"rv": 1, "t": "put"}]
        # a flipped payload byte no longer matches the digest
        torn = dict(unit)
        torn["doc"] = {**doc, "session": {"a": 2}}
        with pytest.raises(ValueError, match="digest"):
            durability.verify_unit(torn)
        # a tampered journal is caught by ITS digest
        torn = dict(unit)
        torn["journal"] = [{"rv": 1, "t": "delete"}]
        with pytest.raises(ValueError, match="digest"):
            durability.verify_unit(torn)

    def test_worker_rejects_corrupt_unit_over_http(self, durable_fleet):
        router, servers = durable_fleet
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "tamper-1"})[0]
            == 201
        )
        src = servers[_owner_idx(router, "tamper-1")]
        code, unit, _ = _req(
            src.port, "GET", "/api/v1/admin/checkpoints/tamper-1"
        )
        assert code == 200 and unit["sha256"]
        unit["sha256"] = "0" * 64
        dst = next(s for s in servers if s is not src)
        code, doc, _ = _req(
            dst.port, "POST", "/api/v1/admin/adopt", {"checkpoints": [unit]}
        )
        assert code == 200
        assert "tamper-1" in doc["rejected"] and doc["adopted"] == []
        assert "digest" in doc["rejected"]["tamper-1"]
        # nothing unknown appeared on the receiver
        code, idx, _ = _req(dst.port, "GET", "/api/v1/admin/checkpoints")
        assert "tamper-1" not in {c["id"] for c in idx["checkpoints"]}

    def test_unknown_checkpoint_is_404(self, durable_fleet):
        _, servers = durable_fleet
        code, _, _ = _req(
            servers[0].port, "GET", "/api/v1/admin/checkpoints/nope-1"
        )
        assert code == 404


class TestJournalReplay:
    def test_replay_is_idempotent_and_double_adopt_is_duplicate(
        self, durable_fleet
    ):
        router, servers = durable_fleet
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "replay-1"})[0]
            == 201
        )
        base = "/api/v1/sessions/replay-1"
        _req(router.port, "PUT", f"{base}/resources/nodes", node("jn0"))
        for i in range(3):
            _req(router.port, "PUT", f"{base}/resources/pods", pod(f"jp{i}"))
        src = servers[_owner_idx(router, "replay-1")]
        code, unit, _ = _req(
            src.port, "GET", "/api/v1/admin/checkpoints/replay-1"
        )
        assert code == 200
        # acknowledged writes ride the journal past the base snapshot
        assert unit.get("journal"), "journaling produced no entries"
        dst = next(s for s in servers if s is not src)
        code, doc, _ = _req(
            dst.port, "POST", "/api/v1/admin/adopt", {"checkpoints": [unit]}
        )
        assert code == 200 and doc["adopted"] == ["replay-1"]
        via_dst = _raw(dst.port, f"{base}/resources/pods")
        via_src = _raw(src.port, f"{base}/resources/pods")
        # base + replay = the exact live state: identical documents in
        # canonical form (checkpoint restore sorts object keys, so raw
        # byte order differs — same values, rvs, and uids)
        assert canonical_bytes(json.loads(via_dst)) == canonical_bytes(
            json.loads(via_src)
        )
        # an idempotent re-push is a duplicate, and changes nothing
        code, doc, _ = _req(
            dst.port, "POST", "/api/v1/admin/adopt", {"checkpoints": [unit]}
        )
        assert code == 200 and doc["duplicate"] == ["replay-1"]
        assert _raw(dst.port, f"{base}/resources/pods") == via_dst

    def test_replica_store_then_promote(self, durable_fleet):
        router, servers = durable_fleet
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "promo-1"})[0]
            == 201
        )
        base = "/api/v1/sessions/promo-1"
        _req(router.port, "PUT", f"{base}/resources/pods", pod("pp0"))
        src = servers[_owner_idx(router, "promo-1")]
        _, unit, _ = _req(
            src.port, "GET", "/api/v1/admin/checkpoints/promo-1"
        )
        dst = next(s for s in servers if s is not src)
        # a replica push stores passively: the session is NOT live there
        code, doc, _ = _req(
            dst.port,
            "POST",
            "/api/v1/admin/adopt",
            {"replica": True, "checkpoints": [unit]},
        )
        assert code == 200 and doc["stored"] == ["promo-1"]
        code, sdoc, _ = _req(dst.port, "GET", "/api/v1/sessions")
        assert "promo-1" not in {s["id"] for s in sdoc["sessions"]}
        # promotion brings it live with the replicated state
        code, doc, _ = _req(
            dst.port, "POST", "/api/v1/admin/adopt", {"promote": ["promo-1"]}
        )
        assert code == 200 and doc["promoted"] == ["promo-1"]
        code, items, _ = _req(dst.port, "GET", f"{base}/resources/pods")
        assert code == 200
        assert {p["metadata"]["name"] for p in items["items"]} == {"pp0"}


class TestRingPlacement:
    def test_owners_prefix_is_owner_and_distinct(self):
        ring = HashRing(["w0", "w1", "w2", "w3"])
        for key in (f"s-{i}" for i in range(64)):
            owners = ring.owners(key, 3)
            assert owners[0] == ring.owner(key)
            assert len(owners) == len(set(owners)) == 3

    def test_join_moves_only_what_the_joiner_now_owns(self):
        keys = [f"sess-{i}" for i in range(256)]
        ring = HashRing(["w0", "w1", "w2"])
        before = {k: ring.owner(k) for k in keys}
        ring.add("w3")
        after = {k: ring.owner(k) for k in keys}
        moved = {k for k in keys if before[k] != after[k]}
        # every moved key moved TO the joiner, nobody else shuffled
        assert all(after[k] == "w3" for k in moved)
        # and the joiner took a minority arc, not the whole ring
        assert 0 < len(moved) < len(keys) / 2

    def test_successor_placement_survives_primary_death(self):
        ring = HashRing(["w0", "w1", "w2"])
        for key in (f"s-{i}" for i in range(64)):
            primary, successor = ring.owners(key, 2)
            ring.remove(primary)
            assert ring.owner(key) == successor
            ring.add(primary)


class TestCircuitBreaker:
    """The state machine (docs/resilience.md), driven directly through
    `_breaker_allow` / `_breaker_record` — deterministic, no sockets."""

    def test_closed_open_halfopen_ladder(self, durable_fleet):
        router, _ = durable_fleet
        w = router.worker_for("default")
        assert w.breaker_state == "closed"
        assert router._breaker_allow(w)
        # failures below the threshold keep the breaker closed
        for _ in range(router.breaker_failures - 1):
            router._breaker_record(w, ok=False)
        assert w.breaker_state == "closed" and router._breaker_allow(w)
        # the threshold failure trips it: calls shed without a socket
        router._breaker_record(w, ok=False)
        assert w.breaker_state == "open"
        assert router._breaker_opens == 1
        assert not router._breaker_allow(w)
        # after the open window ONE probe is admitted, the rest shed
        w.breaker_opened_at -= router.breaker_open_s + 1
        assert router._breaker_allow(w)
        assert w.breaker_state == "half-open"
        assert not router._breaker_allow(w)
        # the probe failing re-opens immediately (and counts the edge)
        router._breaker_record(w, ok=False)
        assert w.breaker_state == "open" and router._breaker_opens == 2
        # a successful half-open probe closes and resets the count
        w.breaker_opened_at -= router.breaker_open_s + 1
        assert router._breaker_allow(w)
        router._breaker_record(w, ok=True)
        assert w.breaker_state == "closed" and w.breaker_failures == 0
        assert router._breaker_allow(w)

    def test_success_resets_the_failure_count(self, durable_fleet):
        router, _ = durable_fleet
        w = router.worker_for("default")
        for _ in range(router.breaker_failures - 1):
            router._breaker_record(w, ok=False)
        router._breaker_record(w, ok=True)
        assert w.breaker_failures == 0
        # the earlier near-trip no longer contributes
        router._breaker_record(w, ok=False)
        assert w.breaker_state == "closed"


class TestCrashKillParity:
    def test_replicated_rehome_matches_uninterrupted_oracle(
        self, durable_fleet, tmp_path
    ):
        """Crash-kill the owner (no drain, no snapshot) after a
        replication round: the successor's promoted replica + journal
        replay must answer byte-identically to a single-process server
        that never crashed — acknowledged writes survive exactly."""
        router, servers = durable_fleet
        solo = SimulatorServer(
            SimulatorService(),
            port=0,
            session_config={"snapshot_dir": str(tmp_path / "solo")},
        ).start()
        try:
            def drive(port):
                assert (
                    _req(port, "POST", "/api/v1/sessions", {"id": "crash-1"})[0]
                    == 201
                )
                base = "/api/v1/sessions/crash-1"
                for i in range(3):
                    _req(
                        port,
                        "PUT",
                        f"{base}/resources/nodes",
                        node(f"cn{i}", cpu="2", mem="4Gi"),
                    )
                for i in range(6):
                    _req(
                        port,
                        "PUT",
                        f"{base}/resources/pods",
                        pod(f"cp{i}", cpu="500m", mem="512Mi"),
                    )
                code, out, _ = _req(port, "POST", f"{base}/schedule")
                assert code == 200 and out["scheduled"] == 6

            drive(router.port)
            drive(solo.port)
            # one replication round ships base + journal to successors
            # (the ticker may have beaten us to it — the digest memo
            # then skips unchanged units; either way a replica is out)
            owner_idx = _owner_idx(router, "crash-1")
            owner_wid = f"w{owner_idx}"
            servers[owner_idx].replication.ship_once()
            stats = servers[owner_idx].replication.stats()
            assert stats["shippedUnits"] >= 1 and stats["shipErrors"] == 0
            # SIGKILL-equivalent: the worker vanishes mid-air
            servers[owner_idx].shutdown()
            for _ in range(3):
                router.probe_once()
            _, fdoc, _ = _req(router.port, "GET", "/api/v1/fleet")
            assert fdoc["sessions"]["crash-1"] != owner_wid
            assert not fdoc["pendingAdopts"]
            via_fleet = _raw(
                router.port, "/api/v1/sessions/crash-1/resources/pods"
            )
            via_solo = _raw(
                solo.port, "/api/v1/sessions/crash-1/resources/pods"
            )
            # identical canonical documents: every acknowledged write
            # (bindings, rvs, uids) survived the crash exactly
            assert canonical_bytes(json.loads(via_fleet)) == canonical_bytes(
                json.loads(via_solo)
            )
        finally:
            solo.shutdown()
