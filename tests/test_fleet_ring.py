"""Consistent-hash ring properties (fleet/ring.py) the router's
affinity, re-home, and roll behavior all lean on: determinism across
instances, stable ownership while the worker set holds, and bounded
(~1/N) movement on join/leave."""

from __future__ import annotations

import pytest

from kube_scheduler_simulator_tpu.fleet.ring import HashRing

KEYS = [f"s-{i:04d}" for i in range(400)] + ["default", "tenant-a.prod"]


def owners(ring, keys=KEYS):
    return {k: ring.owner(k) for k in keys}


def test_empty_ring_owns_nothing():
    ring = HashRing()
    assert len(ring) == 0
    assert ring.owner("anything") is None
    assert ring.owners("anything", 3) == []


def test_replicas_must_be_positive():
    with pytest.raises(ValueError):
        HashRing(replicas=0)


def test_deterministic_across_instances_and_insert_order():
    a = HashRing(["w0", "w1", "w2"])
    b = HashRing(["w2", "w0", "w1"])  # same set, different join order
    assert owners(a) == owners(b)
    # and a third instance built incrementally agrees too
    c = HashRing()
    for wid in ("w1", "w2", "w0"):
        c.add(wid)
    assert owners(a) == owners(c)


def test_affinity_stable_under_reads():
    ring = HashRing(["w0", "w1", "w2"])
    first = owners(ring)
    assert owners(ring) == first  # reads don't perturb ownership
    assert all(w in ("w0", "w1", "w2") for w in first.values())
    # every worker owns SOMETHING at this key count (vnodes spread)
    assert set(first.values()) == {"w0", "w1", "w2"}


def test_add_is_idempotent_and_remove_of_absent_is_noop():
    ring = HashRing(["w0", "w1"])
    before = owners(ring)
    ring.add("w0")
    ring.remove("not-there")
    assert owners(ring) == before


def test_join_moves_only_keys_the_joiner_now_owns():
    ring = HashRing(["w0", "w1", "w2"])
    before = owners(ring)
    ring.add("w3")
    after = owners(ring)
    moved = [k for k in KEYS if before[k] != after[k]]
    # every moved key moved TO the joiner — nobody else gained keys
    assert all(after[k] == "w3" for k in moved)
    # bounded movement: ~1/(N+1) of keys, generously bounded at 2x fair
    assert len(moved) <= len(KEYS) // 2


def test_leave_moves_only_the_leavers_keys():
    ring = HashRing(["w0", "w1", "w2", "w3"])
    before = owners(ring)
    ring.remove("w2")
    after = owners(ring)
    for k in KEYS:
        if before[k] == "w2":
            assert after[k] != "w2"  # re-homed somewhere live
        else:
            assert after[k] == before[k]  # everyone else unmoved


def test_leave_rehomes_to_the_declared_successor():
    ring = HashRing(["w0", "w1", "w2"])
    prefs = {k: ring.owners(k, 2) for k in KEYS}
    ring.remove("w1")
    for k in KEYS:
        if prefs[k][0] == "w1":
            # the key lands exactly where owners(k, 2)[1] promised
            assert ring.owner(k) == prefs[k][1]


def test_single_worker_owns_everything():
    ring = HashRing(["only"])
    assert set(owners(ring).values()) == {"only"}
    ring.remove("only")
    assert ring.owner("default") is None
