"""The fleet & memory observatory (utils/fleetstats.py,
docs/observability.md): the sample ring stays bounded under concurrent
writers, a stats-off run emits nothing AND places byte-identically to a
stats-on run (sampling invariance — the KSS_PROGRAM_TIMING_SAMPLE
precedent), the serving surface exposes the samples
(`GET /api/v1/timeseries`, the `kss_fleet_*`/`kss_device_hbm_*` gauges,
the dashboard's Observability tab), and the broker's speculation
headroom gate (`KSS_SPEC_MEM_HEADROOM_BYTES`) skips background builds
when the devices report no room."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService
from kube_scheduler_simulator_tpu.server.service import SchedulerService
from kube_scheduler_simulator_tpu.utils import broker as broker_mod
from kube_scheduler_simulator_tpu.utils import fleetstats
from kube_scheduler_simulator_tpu.utils import metrics as metrics_mod
from kube_scheduler_simulator_tpu.utils import telemetry

from helpers import node, pod


@pytest.fixture()
def recorder():
    rec = fleetstats.FleetRecorder(capacity=64)
    fleetstats.activate(rec)
    try:
        yield rec
    finally:
        fleetstats.deactivate()


def _store(n_nodes=2, n_pods=4) -> ResourceStore:
    store = ResourceStore()
    for i in range(n_nodes):
        store.apply("nodes", node(f"fn{i}", cpu="4", mem="8Gi"))
    for i in range(n_pods):
        store.apply("pods", pod(f"fp{i}", cpu="100m"))
    return store


# -- the ring -----------------------------------------------------------------


def test_ring_bounded_under_concurrent_writers():
    rec = fleetstats.FleetRecorder(capacity=16)
    threads = [
        threading.Thread(
            target=lambda k=k: [
                rec.push({"session": f"s{k}", "fleet": {}, "i": i})
                for i in range(200)
            ]
        )
        for k in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rec.emitted == 8 * 200
    assert len(rec) == 16
    assert rec.dropped == 8 * 200 - 16
    window = rec.snapshot()
    assert len(window) == 16
    # seq stamps are the ring's order: the window is the newest suffix
    seqs = [s["seq"] for s in window]
    assert seqs == sorted(seqs)
    assert seqs[-1] == 8 * 200 - 1


def test_ring_capacity_and_cadence_env_parse(monkeypatch):
    monkeypatch.setenv(fleetstats.CAP_VAR, "not-a-number")
    assert fleetstats.ring_capacity_from_env() == fleetstats.DEFAULT_RING_CAP
    monkeypatch.setenv(fleetstats.CAP_VAR, "-3")
    assert fleetstats.ring_capacity_from_env() == fleetstats.DEFAULT_RING_CAP
    monkeypatch.setenv(fleetstats.SAMPLE_VAR, "0")
    assert fleetstats.sample_every_from_env() == 1
    monkeypatch.setenv(fleetstats.SAMPLE_VAR, "4")
    assert fleetstats.sample_every_from_env() == 4


def test_subscribers_receive_samples_and_never_break_the_push():
    rec = fleetstats.FleetRecorder(capacity=4)
    seen: list = []
    rec.subscribe(seen.append)
    rec.subscribe(lambda s: 1 / 0)  # a dead subscriber must be contained
    rec.push({"session": "default", "fleet": {}})
    assert len(seen) == 1 and seen[0]["seq"] == 0


# -- sampling -----------------------------------------------------------------


def test_off_by_default_emits_nothing(monkeypatch):
    monkeypatch.delenv(fleetstats.ENV_VAR, raising=False)
    assert fleetstats.active() is None
    svc = SchedulerService(_store())
    placements, _, _ = svc.schedule_gang(record=False)
    assert any(v for v in placements.values())
    assert fleetstats.active() is None  # still off: nothing armed a ring


def test_pass_sampling_populates_ring(recorder):
    svc = SchedulerService(_store(n_nodes=2, n_pods=3))
    svc.schedule_gang(record=False)
    svc.store.apply("pods", pod("fp-late"))
    svc.schedule()  # the sequential finish path samples too
    assert recorder.emitted == 2
    s = recorder.snapshot()[0]
    assert s["session"] == "default"
    assert s["mode"] == "gang"
    assert s["passId"] == 1
    fleet = s["fleet"]
    assert fleet["nodes"] == 2
    assert fleet["pendingPods"] == 0  # everything placed
    assert sum(fleet["utilization"]["histogram"]) == 2  # one slot per node
    assert 0.0 <= fleet["utilization"]["mean"] <= fleet["utilization"]["max"] <= 1.0
    # two equally-loaded nodes split free capacity: the largest free
    # block is half the total -> fragmentation index 0.5 per resource
    assert fleet["fragmentationIndex"] == pytest.approx(0.5, abs=0.05)
    assert "cpu" in fleet["fragmentation"]
    buffers = s["buffers"]
    assert buffers["liveBytes"] > 0
    assert buffers["deltaRetainedBytes"] > 0
    assert buffers["warmEngines"] >= 1
    assert s["devices"], "device list must not be empty on a live backend"


def test_sample_cadence_skips_passes(recorder, monkeypatch):
    monkeypatch.setenv(fleetstats.SAMPLE_VAR, "3")
    store = _store()
    store.apply("pods", pod("never-fits", cpu="100"))  # stays pending
    svc = SchedulerService(store)
    for _ in range(4):
        svc.schedule_gang(record=False)
    # every pass reaches the finish path (the queue never empties);
    # passes 1 and 4 sample, 2 and 3 skip the cadence
    assert recorder.emitted == 2


def test_pending_age_tracking_across_samples(recorder):
    store = _store(n_nodes=1, n_pods=0)
    store.apply("pods", pod("huge", cpu="100"))  # can never fit
    svc = SchedulerService(store)
    svc.schedule_gang(record=False)
    svc.schedule_gang(record=False)
    first, second = recorder.snapshot()
    assert first["fleet"]["pendingPods"] == 1
    ages1 = first["fleet"]["pendingAges"]
    ages2 = second["fleet"]["pendingAges"]
    assert ages1["count"] == ages2["count"] == 1
    # the pod was first seen pending at sample 1: its age grows
    assert ages2["maxSeconds"] >= ages1["maxSeconds"]


def test_counter_tracks_emitted_when_tracing_on(recorder):
    span_rec = telemetry.SpanRecorder(capacity=4096)
    telemetry.activate(span_rec)
    try:
        svc = SchedulerService(_store())
        svc.schedule_gang(record=False)
    finally:
        telemetry.deactivate()
    counters = {
        e["name"] for e in span_rec.snapshot() if e.get("ph") == "C"
    }
    assert {"fleet.pendingPods", "fleet.utilizationMax",
            "fleet.fragmentationIndex"} <= counters


# -- sampling invariance (the acceptance pin) ---------------------------------


def _placements(armed: bool, monkeypatch) -> dict:
    monkeypatch.delenv(fleetstats.ENV_VAR, raising=False)
    monkeypatch.delenv(fleetstats.SAMPLE_VAR, raising=False)
    if armed:
        fleetstats.activate(fleetstats.FleetRecorder(capacity=64))
    else:
        fleetstats.activate(None)
    try:
        svc = SchedulerService(_store(n_nodes=3, n_pods=8))
        placements, _, _ = svc.schedule_gang(record=False)
        svc.store.apply("pods", pod("late-1", cpu="100m"))
        second, _, _ = svc.schedule_gang(record=False)
    finally:
        fleetstats.deactivate()
    return {**placements, **second}


def test_stats_on_is_placement_invariant(monkeypatch):
    off = _placements(False, monkeypatch)
    on = _placements(True, monkeypatch)
    assert off == on
    assert any(v for v in off.values())


# -- the speculation headroom gate --------------------------------------------


def test_speculation_memory_ok_defaults_open(monkeypatch):
    monkeypatch.delenv(fleetstats.HEADROOM_VAR, raising=False)
    assert fleetstats.speculation_memory_ok()


def test_headroom_gate_skips_speculation(monkeypatch):
    b = broker_mod.CompileBroker(speculative=True)
    monkeypatch.setenv(fleetstats.HEADROOM_VAR, str(1 << 30))
    monkeypatch.setattr(fleetstats, "hbm_headroom_bytes", lambda: 1024)
    assert b.speculate(("t", 1), lambda: None) is False
    assert b.stats()["speculationMemSkips"] == 1
    # room again: the same broker arms normally
    monkeypatch.setattr(
        fleetstats, "hbm_headroom_bytes", lambda: 4 << 30
    )
    assert b.speculate(("t", 2), lambda: None) is True
    b.drain(timeout=10)


def test_headroom_gate_passes_when_unmeasurable(monkeypatch):
    # no allocator stats (CPU): the gate must not block what it cannot
    # measure
    monkeypatch.setenv(fleetstats.HEADROOM_VAR, str(1 << 30))
    monkeypatch.setattr(fleetstats, "hbm_headroom_bytes", lambda: None)
    assert fleetstats.speculation_memory_ok()


# -- the serving surface ------------------------------------------------------


def _get(port: int, path: str):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=300
    ) as r:
        return r.status, r.read().decode()


@pytest.fixture()
def armed_server(recorder):
    server = SimulatorServer(SimulatorService(), port=0).start()
    try:
        server.service.store.apply("nodes", node("wn0"))
        server.service.store.apply("nodes", node("wn1"))
        server.service.store.apply("pods", pod("wp0"))
        server.service.scheduler.schedule()
        yield server
    finally:
        server.shutdown()


def test_timeseries_route_serves_the_window(armed_server):
    _, body = _get(armed_server.port, "/api/v1/timeseries")
    doc = json.loads(body)
    assert doc["enabled"] is True
    assert doc["emitted"] >= 1
    assert doc["samples"], "a scheduled pass must have produced a sample"
    s = doc["samples"][-1]
    assert s["session"] == "default"
    assert "fleet" in s and "buffers" in s and "devices" in s
    # windowing: limit keeps the newest suffix, sinceSeq resumes
    _, body = _get(armed_server.port, "/api/v1/timeseries?limit=0")
    assert json.loads(body)["samples"] == []
    seq = s["seq"]
    _, body = _get(
        armed_server.port, f"/api/v1/timeseries?sinceSeq={seq}"
    )
    assert json.loads(body)["samples"] == []
    status, _ = _get_error(
        armed_server.port, "/api/v1/timeseries?limit=bogus"
    )
    assert status == 400


def _get_error(port: int, path: str):
    try:
        return _get(port, path)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_timeseries_nested_session_route_filters(armed_server):
    # a second session's pass lands its own labeled samples
    req = urllib.request.Request(
        f"http://127.0.0.1:{armed_server.port}/api/v1/sessions",
        data=json.dumps({"name": "tenant"}).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        sid = json.loads(r.read())["id"]
    for kind, obj in (("nodes", node("tn0")), ("pods", pod("tp0"))):
        req = urllib.request.Request(
            f"http://127.0.0.1:{armed_server.port}"
            f"/api/v1/sessions/{sid}/resources/{kind}",
            data=json.dumps(obj).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=300).read()
    req = urllib.request.Request(
        f"http://127.0.0.1:{armed_server.port}"
        f"/api/v1/sessions/{sid}/schedule",
        data=b"",
        method="POST",
    )
    urllib.request.urlopen(req, timeout=300).read()
    _, body = _get(
        armed_server.port, f"/api/v1/sessions/{sid}/timeseries"
    )
    doc = json.loads(body)
    assert doc["samples"]
    assert all(s["session"] == sid for s in doc["samples"])
    # the legacy route still carries every session's samples
    _, body = _get(armed_server.port, "/api/v1/timeseries")
    sessions = {s["session"] for s in json.loads(body)["samples"]}
    assert {"default", sid} <= sessions


def test_prometheus_gauges_render_and_parse(armed_server):
    _, text = _get(armed_server.port, "/api/v1/metrics?format=prometheus")
    families = metrics_mod.parse_prometheus_text(text)
    for fam in (
        "kss_fleet_pending_pods",
        "kss_fleet_utilization_mean",
        "kss_fleet_utilization_max",
        "kss_fleet_fragmentation_index",
        "kss_fleet_live_buffer_bytes",
        "kss_fleet_samples_total",
    ):
        assert fam in families, f"{fam} missing from the exposition"
    samples = families["kss_fleet_pending_pods"]["samples"]
    assert any(labels.get("session") == "default" for _n, labels, _v in samples)


def test_unarmed_server_answers_honest_empty_documents():
    fleetstats.activate(None)
    server = SimulatorServer(SimulatorService(), port=0).start()
    try:
        _, body = _get(server.port, "/api/v1/timeseries")
        doc = json.loads(body)
        assert doc == {
            "enabled": False,
            "capacity": 0,
            "emitted": 0,
            "dropped": 0,
            "samples": [],
        }
        _, text = _get(server.port, "/api/v1/metrics?format=prometheus")
        assert "kss_fleet_" not in text
    finally:
        server.shutdown()
        fleetstats.deactivate()


def test_dashboard_serves_the_observability_tab(armed_server):
    _, html = _get(armed_server.port, "/")
    assert "Observability" in html
    assert "/api/v1/timeseries" in html
    assert "/api/v1/events" in html
    assert "obspane" in html and "drawSparks" in html


def test_sse_stream_carries_fleet_events(armed_server):
    import time

    # a pending pod so the triggered pass is non-empty (empty passes
    # never reach the finish path and sample nothing)
    armed_server.service.store.apply("pods", pod("wp-sse"))
    req = urllib.request.Request(
        f"http://127.0.0.1:{armed_server.port}/api/v1/events"
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        # drain the connect-time metrics event, then trigger a pass and
        # expect its fleet sample on the stream
        first = None
        for _ in range(16):
            line = r.readline().decode()
            if line.startswith("event:"):
                first = line.split(":", 1)[1].strip()
                break
        assert first == "metrics"
        t = threading.Thread(
            target=armed_server.service.scheduler.schedule, daemon=True
        )
        t.start()
        saw_fleet = False
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = r.readline().decode()
            if not line:
                break
            if line.startswith("event:") and "fleet" in line:
                saw_fleet = True
                break
        t.join(timeout=60)
        assert saw_fleet, "no fleet event arrived after a scheduled pass"


# -- the census helpers -------------------------------------------------------


def test_device_memory_never_raises_and_shapes_entries():
    devices = fleetstats.device_memory()
    assert isinstance(devices, list) and devices
    for d in devices:
        assert "id" in d and "platform" in d


def test_buffer_census_reports_ledger_and_sessions(monkeypatch):
    from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod

    monkeypatch.setattr(
        ledger_mod.LEDGER, "memory_bytes_total", lambda: 12345
    )
    fleetstats.set_session_provider(lambda: ["default", "s-a", "s-b"])
    try:
        census = fleetstats.buffer_census()
    finally:
        fleetstats.set_session_provider(None)
    assert census["ledgerMemoryBytes"] == 12345
    assert census["sessions"] == 3


def test_deleted_session_drops_ages_and_exposition_series():
    rec = fleetstats.FleetRecorder(capacity=16)
    rec._pending_seen[("s-dead", "default", "p0")] = 0.0
    rec._pending_seen[("s-live", "default", "p1")] = 0.0
    rec.push({"session": "s-dead", "fleet": {"pendingPods": 9,
              "utilization": {"mean": 0.1, "max": 0.2},
              "fragmentationIndex": 0.3}, "buffers": {}, "devices": []})
    rec.push({"session": "s-live", "fleet": {"pendingPods": 1,
              "utilization": {"mean": 0.1, "max": 0.2},
              "fragmentationIndex": 0.3}, "buffers": {}, "devices": []})
    rec.drop_session("s-dead")
    assert list(rec._pending_seen) == [("s-live", "default", "p1")]
    # the exposition drops the dead tenant's frozen gauges but keeps
    # the ring history (the time-series records what happened)
    fleetstats.set_session_provider(lambda: ["s-live"])
    try:
        text = fleetstats.render_prometheus(rec)
    finally:
        fleetstats.set_session_provider(None)
    assert 's-live' in text and 's-dead' not in text
    assert len(rec.snapshot()) == 2


def test_manager_provider_is_weakref_backed():
    import gc

    from kube_scheduler_simulator_tpu.server.sessions import SessionManager

    mgr = SessionManager(SimulatorService())
    assert fleetstats.known_sessions() == {"default"}
    mgr.shutdown()
    del mgr
    gc.collect()
    # the dead manager must not stay reachable through the hook: the
    # weakref-backed provider answers None (= no plane, no filter)
    assert fleetstats.known_sessions() is None
