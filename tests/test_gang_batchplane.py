"""Gang passes through the batch plane (``batch.gang.run``).

The fused `gang.fixpoint` vmapped over the session axis — the gang half
of the cross-tenant continuous-batching contract (the sequential half
lives in test_batchplane.py, whose fixtures this file shares). The
parity pin is the same hard contract: the plane may change throughput
and latency, never an answer. Covered here: sync + async parity
(placements, rounds-to-fixpoint, store write-back bytes) on both the
plain and the PREEMPTION fixtures, per-tenant ledger attribution of the
window's ONE device dispatch, the mid-batch session DELETE, and the
batched-failure → per-session resilience-ladder fallback.

Solo baselines are memoized module-wide: every test compares against
the same once-computed solo answer, so the file pays each baseline
compile exactly once.
"""

from __future__ import annotations

import functools
import json
import threading
import time

import pytest

from kube_scheduler_simulator_tpu.server.batchplane import (
    BATCH_GANG_LABEL,
    BatchPlane,
)
from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod

from test_batchplane import N, _armed_manager, _manager, _snapshot


@functools.lru_cache(maxsize=None)
def _solo_gang(preempt: bool = False) -> dict:
    """Solo gang baselines (record=False, unarmed manager), computed
    ONCE per fixture for the whole module:
    {i: (placements, rounds, store_pods_doc)}. Callers treat the
    returned structures as read-only."""
    mgr = _manager()
    out = {}
    try:
        for i in range(N):
            sess, errs = mgr.create(
                name=f"gsolo{i}", snapshot=_snapshot(i, preempt)
            )
            assert not errs
            placements, rounds, _ = sess.service.scheduler.schedule_gang(
                record=False
            )
            store_doc = json.dumps(
                sess.service.store.list("pods"), sort_keys=True
            )
            out[i] = (placements, rounds, store_doc)
    finally:
        mgr.shutdown()
    return out


def _concurrent_gang(mgr, sessions, mode: str = "sync"):
    """Drive every session's gang pass (record=False) concurrently,
    barrier-aligned so all enroll in one window. Returns
    {i: (placements, rounds)} (async mode: {i: scheduled_count})."""
    out, errors = {}, {}
    barrier = threading.Barrier(len(sessions))

    def run(i):
        try:
            barrier.wait(timeout=30)
            svc = sessions[i].service
            with mgr.pass_slot():
                if mode == "async":
                    handle = svc.scheduler.begin_gang_pass()
                    out[i] = handle.resolve()
                else:
                    placements, rounds, _ = svc.scheduler.schedule_gang(
                        record=False
                    )
                    out[i] = (placements, rounds)
        except Exception as e:  # noqa: BLE001 — surfaced by the assert
            errors[i] = repr(e)

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(len(sessions))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, errors
    assert len(out) == len(sessions)
    return out


class TestGangBatching:
    def test_sync_parity_counters_and_attribution(self, monkeypatch):
        """N sessions' gang passes batch into ONE window: placements,
        rounds-to-fixpoint, and store write-back bytes identical to
        solo — on the PREEMPTION fixture, so the fused program's
        cond-guarded phase + resume run under vmap. The window is ONE
        ledger-pinned device dispatch (`batch.gang.run` calls == 1)
        with every tenant attributed and the solo fused program silent.
        """
        solo = _solo_gang(True)  # before the ledger reset below
        monkeypatch.setenv("KSS_PROGRAM_LEDGER", "1")
        # reset BEFORE building the armed manager: its engines hook the
        # ledger at jit-wrap time, so their records survive the reset
        # (pre-existing wrappers' handles would be orphaned instead)
        ledger_mod.LEDGER.reset()
        try:
            mgr, _plane = _armed_manager()
            try:
                sessions = [
                    mgr.create(name=f"t{i}", snapshot=_snapshot(i, True))[0]
                    for i in range(N)
                ]
                sids = [s.id for s in sessions]
                out = _concurrent_gang(mgr, sessions)
                for i in range(N):
                    placements, rounds = out[i]
                    assert placements == solo[i][0], f"session {i} diverged"
                    assert rounds == solo[i][1], f"session {i} rounds diverged"
                    got = json.dumps(
                        sessions[i].service.store.list("pods"), sort_keys=True
                    )
                    assert got == solo[i][2], f"session {i} store diverged"
                default_phases = (
                    mgr.get("default").service.scheduler.metrics.snapshot()
                )
                assert default_phases["phases"]["batchWindows"] == 1
                assert default_phases["phases"]["batchOccupancySum"] == N
                for i, s in enumerate(sessions):
                    phases = s.service.scheduler.metrics.snapshot()["phases"]
                    assert phases["batchedGangPasses"] == 1
                    assert phases["batchedPasses"] == 1
                    assert phases["soloFallbacks"] == 0
                    assert phases["gangFixpointRounds"] == solo[i][1]
                recs = [
                    rec
                    for rec in ledger_mod.LEDGER.snapshot()["programs"]
                    if rec["label"] == BATCH_GANG_LABEL
                ]
                assert len(recs) == 1
                assert recs[0]["calls"] == 1
                for sid in sids:
                    assert sid in recs[0]["sessions"], (
                        f"{sid} missing from {recs[0]['sessions']}"
                    )
                assert sum(recs[0]["sessions"].values()) == N
                # the solo fused program never fired: the window's one
                # dispatch served every pass (the memoized baselines
                # above predate the reset, so any calls here would be
                # the armed manager's own)
                solo_recs = [
                    rec
                    for rec in ledger_mod.LEDGER.snapshot()["programs"]
                    if rec["label"] == "gang.fixpoint" and rec["calls"]
                ]
                assert not solo_recs
            finally:
                mgr.shutdown()
        finally:
            ledger_mod.LEDGER.reset()

    def test_async_parity(self):
        """begin_gang_pass/resolve (the async pipeline's split) through
        the batch plane: store write-backs identical to the SYNC solo
        baseline — the split must not change the answer either."""
        solo = _solo_gang(False)
        mgr, _plane = _armed_manager()
        try:
            sessions = [
                mgr.create(name=f"t{i}", snapshot=_snapshot(i))[0]
                for i in range(N)
            ]
            _concurrent_gang(mgr, sessions, mode="async")
            for i, s in enumerate(sessions):
                got = json.dumps(s.service.store.list("pods"), sort_keys=True)
                assert got == solo[i][2], f"session {i} store diverged"
                phases = s.service.scheduler.metrics.snapshot()["phases"]
                assert phases["batchedGangPasses"] == 1
                assert phases["soloFallbacks"] == 0
        finally:
            mgr.shutdown()

    def test_mid_batch_session_delete(self):
        """A session DELETEd while its gang pass waits in a window: the
        pass still completes (write-backs land on the orphaned store),
        and the surviving enrollee stays identical to solo."""
        solo = _solo_gang(False)
        # max_sessions=3 so a 2-enrollee window stays OPEN (timer flush)
        mgr, _plane = _armed_manager(window_ms=1000.0, max_sessions=3)
        try:
            a, _ = mgr.create(name="a", snapshot=_snapshot(0))
            b, _ = mgr.create(name="b", snapshot=_snapshot(1))
            out, errors = {}, {}
            barrier = threading.Barrier(3)

            def run(i, sess):
                try:
                    barrier.wait(timeout=30)
                    with mgr.pass_slot():
                        placements, rounds, _ = (
                            sess.service.scheduler.schedule_gang(record=False)
                        )
                        out[i] = (placements, rounds)
                except Exception as e:  # noqa: BLE001
                    errors[i] = repr(e)

            def deleter():
                barrier.wait(timeout=30)
                time.sleep(0.2)  # mid-window: both passes enrolled
                mgr.delete(b.id)

            ts = [
                threading.Thread(target=run, args=(0, a)),
                threading.Thread(target=run, args=(1, b)),
                threading.Thread(target=deleter),
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
            assert not errors, errors
            assert out[0] == (solo[0][0], solo[0][1])
            # the orphaned pass still answered
            assert out[1] == (solo[1][0], solo[1][1])
            with pytest.raises(Exception):
                mgr.get(b.id)
        finally:
            mgr.shutdown()

    def test_batched_failure_falls_back_per_session(self, monkeypatch):
        """ANY failure inside the batched gang execution marks every
        enrollee solo: each pass completes on its own dispatch ladder
        with placements identical to solo — the plane can degrade
        throughput, never correctness."""
        solo = _solo_gang(False)
        monkeypatch.setattr(
            BatchPlane,
            "_execute_inner",
            lambda self, kind, key, items: (_ for _ in ()).throw(
                RuntimeError("injected batch failure")
            ),
        )
        mgr, _plane = _armed_manager()
        try:
            sessions = [
                mgr.create(name=f"t{i}", snapshot=_snapshot(i))[0]
                for i in range(N)
            ]
            out = _concurrent_gang(mgr, sessions)
            for i in range(N):
                assert out[i] == (solo[i][0], solo[i][1]), (
                    f"session {i} diverged after the failed batch"
                )
                phases = sessions[i].service.scheduler.metrics.snapshot()[
                    "phases"
                ]
                assert phases["soloFallbacks"] == 1
                assert phases["batchedGangPasses"] == 0
        finally:
            mgr.shutdown()
