"""Gang-mode result records (VERDICT r4 #6).

The reference's product is the per-pod scheduling trace flushed as 13
annotations (reference resultstore/store.go:129-190). Round 5 gives the
gang scheduler a record path: `run_recorded()` tracks bind rounds,
`results()` replays the chronology and decodes through the sequential
engine's `results()` — one definition of the wire format.

Strong cases pinned here:
  * placements of the record path are bit-identical to `run()`;
  * a preemption-phase-dominated workload produces records IDENTICAL to
    the sequential engine's (the phase replay IS the sequential record
    segment);
  * a single-pod run's record equals the sequential record exactly;
  * structural wire-format checks on a mixed synthetic workload.
"""

from __future__ import annotations

import numpy as np
from kube_scheduler_simulator_tpu.engine import (
    TPU32,
    BatchedScheduler,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.engine.engine import supported_config
from kube_scheduler_simulator_tpu.engine.gang import GangScheduler
from kube_scheduler_simulator_tpu.synth import synthetic_cluster

from helpers import node, pod


def _ann_by_pod(results):
    """Last record per pod wins (the service write-back rule)."""
    out = {}
    for r in results:
        out[(r.pod_namespace, r.pod_name)] = (r.status, r.to_annotations())
    return out


class TestGangRecords:
    def test_single_pod_record_equals_sequential(self):
        nodes = [node(f"n{i}", cpu="4", pods="8") for i in range(3)]
        pds = [pod("solo", cpu="1")]
        enc = encode_cluster(nodes, pds, supported_config(), policy=TPU32)
        gang = GangScheduler(enc)
        g = _ann_by_pod(gang.results())
        seq = BatchedScheduler(enc, record=True)
        s = _ann_by_pod(seq.results())
        assert g == s

    def test_recorded_placements_match_run(self):
        nodes, pds = synthetic_cluster(16, 64, seed=9)
        enc = encode_cluster(nodes, pds, supported_config(), policy=TPU32)
        want_state, _ = GangScheduler(enc, chunk=32).run()
        gang = GangScheduler(enc, chunk=32)
        got_state, _ = gang.run_recorded()
        np.testing.assert_array_equal(
            np.asarray(want_state.assignment), np.asarray(got_state.assignment)
        )

    def test_structural_wire_format_on_synthetic(self):
        nodes, pds = synthetic_cluster(16, 64, seed=9)
        enc = encode_cluster(nodes, pds, supported_config(), policy=TPU32)
        gang = GangScheduler(enc, chunk=32)
        results = gang.results()
        placements = gang.placements()
        recs = _ann_by_pod(results)
        assert set(recs) == set(placements)
        # key-set parity with the sequential wire format
        seq = BatchedScheduler(enc, record=True)
        seq_keys = {
            k
            for _, (status, ann) in _ann_by_pod(seq.results()).items()
            if status == "Scheduled"
            for k in ann
        }
        for key, node_name in placements.items():
            status, ann = recs[key]
            if node_name:
                assert status == "Scheduled"
                assert ann["scheduler-simulator/selected-node"] == node_name
                assert set(ann) == seq_keys, key
            else:
                assert status in ("Unschedulable",)

    def test_preemption_phase_records_equal_sequential(self):
        """All queue pods need eviction -> gang rounds bind nothing and
        the phase replays the whole queue through the sequential step:
        records must be IDENTICAL to the sequential engine's."""
        from test_engine_parity_preempt import preempt_config

        nodes = [node(f"n{i}", cpu="2", pods="8") for i in range(4)]
        pds = [
            pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}")
            for i in range(4)
        ] + [pod(f"high-{i}", cpu="1200m", priority=100) for i in range(3)]
        enc = encode_cluster(nodes, pds, preempt_config(), policy=TPU32)
        gang = GangScheduler(enc)
        g_results = gang.results()
        seq = BatchedScheduler(enc, record=True)
        s_results = seq.results()
        # identical record STREAMS (count, order within pod, content) —
        # nominated pods carry two records in both engines
        assert len(g_results) == len(s_results)
        g_nom = [r for r in g_results if r.status == "Nominated"]
        assert g_nom, "workload did not exercise preemption"
        for gr, sr in zip(g_results, s_results):
            assert (gr.pod_namespace, gr.pod_name, gr.status) == (
                sr.pod_namespace,
                sr.pod_name,
                sr.status,
            )
            assert gr.to_annotations() == sr.to_annotations()

    def test_selected_node_is_committed_node_not_argmax(self):
        """Contention: two identical pods, one feasible node each round
        winner takes argmax — the loser's record still reports its
        COMMITTED node (the gang caveat documented on the class)."""
        nodes = [node("a", cpu="2", pods="8"), node("b", cpu="2", pods="8")]
        pds = [pod("p0", cpu="1"), pod("p1", cpu="1")]
        enc = encode_cluster(nodes, pds, supported_config(), policy=TPU32)
        gang = GangScheduler(enc)
        recs = _ann_by_pod(gang.results())
        placements = gang.placements()
        scheduled = {k: v for k, v in placements.items() if v}
        assert len(scheduled) == 2
        for key, node_name in scheduled.items():
            _, ann = recs[key]
            assert (
                ann["scheduler-simulator/selected-node"]
                == node_name
            )

    def test_results_subset_decode(self):
        nodes, pds = synthetic_cluster(8, 24, seed=3)
        enc = encode_cluster(nodes, pds, supported_config(), policy=TPU32)
        gang = GangScheduler(enc)
        all_recs = _ann_by_pod(gang.results())
        some = sorted(all_recs)[:3]
        subset = _ann_by_pod(GangScheduler(enc).results(pods=set(some)))
        assert set(subset) == set(some)
        for k in some:
            assert subset[k] == all_recs[k]

    def test_windowed_run_records_decode(self):
        """eval_window composes with the record path: the tracked
        program carries the same offset-sweep rounds, and the replay
        re-evaluates each pod against its bind round's start state —
        records must decode the full wire format with selectedNode
        matching the (windowed) placements."""
        nodes, pds = synthetic_cluster(8, 48, seed=6)
        enc = encode_cluster(nodes, pds, supported_config(), policy=TPU32)
        gang = GangScheduler(enc, chunk=8, eval_window=8)
        recs = _ann_by_pod(gang.results())
        placements = gang.placements()
        assert set(recs) == set(placements)
        for key, node_name in placements.items():
            status, ann = recs[key]
            assert len(ann) >= 13
            assert ann["scheduler-simulator/selected-node"] == node_name
        # record-path placements == run() placements (same program)
        again = GangScheduler(enc, chunk=8, eval_window=8)
        again.run()
        assert placements == again.placements()
