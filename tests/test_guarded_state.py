"""The KSS6xx guarded-state witness, runtime half (utils/locking.py,
KSS_RACE_CHECK=1): descriptor semantics, sampling, construction
exemption, inference-driven instrumentation of the live classes, and
the static/runtime map agreement.

The 4-thread session stress under the armed witness lives in
tests/test_lock_witness.py (`test_concurrent_sessions_zero_unguarded_
access`); the static analyzer's negative trees live in
tests/test_static_analysis.py.
"""

import threading

import pytest

from kube_scheduler_simulator_tpu.utils import locking
from kube_scheduler_simulator_tpu.utils.locking import (
    GuardedAttr,
    UnguardedAccess,
    WitnessLock,
    WitnessRLock,
    install_guards,
)


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv(locking.RACE_ENV_VAR, "1")
    monkeypatch.setenv(locking.ENV_VAR, "0")
    locking.WITNESS.reset()
    yield
    locking.WITNESS.reset()


def _guarded_class():
    class T:
        def __init__(self):
            self._lock = locking.make_lock("test.guard")
            self.x = 0

        def bump(self):
            with self._lock:
                self.x += 1
                return self.x

    install_guards(T, {"x": ("_lock",)})
    return T


def _armed_instance(cls):
    t = cls()
    t.__dict__["_kss_guard_armed"] = True
    return t


# -- descriptor semantics -----------------------------------------------------


def test_unguarded_read_and_write_raise(armed):
    t = _armed_instance(_guarded_class())
    with pytest.raises(UnguardedAccess, match="read of T.x"):
        _ = t.x
    with pytest.raises(UnguardedAccess, match="write of T.x"):
        t.x = 7


def test_guarded_access_passes_and_stores_in_dict(armed):
    t = _armed_instance(_guarded_class())
    assert t.bump() == 1
    with t._lock:
        t.x = 41
        assert t.x == 41
    # the value lives under the real name: vars()/state-dump code works
    assert t.__dict__["x"] == 41


def test_construction_is_exempt_until_armed(armed):
    T = _guarded_class()
    t = T()  # __init__ writes x with no lock held: allowed (unarmed)
    assert t.__dict__["x"] == 0
    # still unarmed: accesses pass (the guard_inferred decorator arms
    # instances only after __init__ returns, and only when the knob was
    # set at construction)
    assert t.x == 0


def test_held_by_any_thread_is_sufficient(armed):
    # the dispatch→resolve shape: thread A acquires, thread B accesses
    # while the lock is still held — legal under the witness contract
    t = _armed_instance(_guarded_class())
    t._lock.acquire()
    seen = []

    def other():
        seen.append(t.x)

    th = threading.Thread(target=other)
    th.start()
    th.join(timeout=5)
    t._lock.release()
    assert seen == [0]


def test_unwrapped_lock_fails_open(monkeypatch):
    # instances built while the knob was OFF carry plain locks: the
    # descriptor cannot witness them and must not false-positive
    monkeypatch.delenv(locking.RACE_ENV_VAR, raising=False)
    monkeypatch.delenv(locking.ENV_VAR, raising=False)
    T = _guarded_class()
    t = _armed_instance(T)
    assert t.x == 0  # plain threading.Lock: fail open, no raise


def test_sampling_checks_every_nth_access(monkeypatch):
    monkeypatch.setenv(locking.RACE_ENV_VAR, "1")
    monkeypatch.setenv(locking.RACE_SAMPLE_ENV_VAR, "3")

    class S:
        def __init__(self):
            self._lock = locking.make_lock("test.sample")
            self.y = 0

    install_guards(S, {"y": ("_lock",)})
    s = _armed_instance(S)
    raised = 0
    for _ in range(6):
        try:
            _ = s.y
        except UnguardedAccess:
            raised += 1
    # sample rate 3: exactly every 3rd access is checked (and violates)
    assert raised == 2


def test_missing_attr_raises_attributeerror(armed):
    t = _armed_instance(_guarded_class())
    with t._lock:
        with pytest.raises(AttributeError):
            _ = t.__class__.__dict__["x"].__get__(
                type("E", (), {"__dict__": {}})(), None
            )


def test_delete_goes_through_the_guard(armed):
    t = _armed_instance(_guarded_class())
    with pytest.raises(UnguardedAccess, match="delete of T.x"):
        del t.x
    with t._lock:
        del t.x
    assert "x" not in t.__dict__


def test_class_level_default_is_preserved(armed):
    # the dataclass simple-default shape: a plain class-level value the
    # instance may rely on falling back to — the descriptor shadows it
    # but keeps it as the read fallback (the witness only observes)
    class D:
        flag = False

        def __init__(self):
            self._lock = locking.make_lock("test.default")

    install_guards(D, {"flag": ("_lock",)})
    d = _armed_instance(D)
    with d._lock:
        assert d.flag is False  # falls back to the shadowed default
        d.flag = True
        assert d.flag is True


def test_property_is_never_shadowed(armed):
    class P:
        def __init__(self):
            self._lock = locking.make_lock("test.prop")

        @property
        def x(self):
            return 41

    install_guards(P, {"x": ("_lock",)})
    p = _armed_instance(P)
    assert p.x == 41  # untouched: shadowing a descriptor would change behavior
    assert not isinstance(vars(P)["x"], GuardedAttr)


# -- held_anywhere probes -----------------------------------------------------


def test_witness_lock_held_anywhere():
    lk = WitnessLock("probe.lock", locking.LockWitness())
    assert not lk.held_anywhere()
    with lk:
        assert lk.held_anywhere()
    assert not lk.held_anywhere()


def test_witness_rlock_held_anywhere_outer_only():
    lk = WitnessRLock("probe.rlock", locking.LockWitness())
    assert not lk.held_anywhere()
    with lk:
        with lk:  # re-entrant: still held
            assert lk.held_anywhere()
        assert lk.held_anywhere()
    assert not lk.held_anywhere()


def test_race_check_arms_wrappers_without_lock_check(monkeypatch):
    monkeypatch.delenv(locking.ENV_VAR, raising=False)
    monkeypatch.setenv(locking.RACE_ENV_VAR, "1")
    assert isinstance(locking.make_lock("x"), WitnessLock)
    assert isinstance(locking.make_rlock("x"), WitnessRLock)


# -- inference-driven instrumentation ----------------------------------------


def test_guard_inferred_arms_live_classes(armed):
    from kube_scheduler_simulator_tpu.utils.broker import CompileBroker

    broker = CompileBroker(speculative=False)
    assert broker.__dict__.get("_kss_guard_armed") is True
    # a claimed attribute got a descriptor on the class
    assert isinstance(
        type(broker).__dict__.get("_engines"), GuardedAttr
    )
    # normal (locked) use keeps working
    assert broker.peek(("k",)) is None
    broker.get(("k",), lambda: object())
    assert broker.stats()["compileMisses"] == 1
    # and a raw unguarded poke at claimed state raises
    with pytest.raises(UnguardedAccess):
        broker._engines["evil"] = object()


def test_runtime_map_matches_static_inference(armed):
    # the two halves derive from ONE inference: every descriptor
    # installed on CompileBroker corresponds to a static claim
    from kube_scheduler_simulator_tpu.analysis import guarded_state
    from kube_scheduler_simulator_tpu.analysis.core import SourceTree
    from kube_scheduler_simulator_tpu.utils.broker import CompileBroker

    CompileBroker(speculative=False)  # triggers instrumentation
    cmap = guarded_state.protection_map(SourceTree.load())[
        ("utils/broker.py", "CompileBroker")
    ]
    installed = {
        name
        for name, v in vars(CompileBroker).items()
        if isinstance(v, GuardedAttr)
    }
    assert installed == set(cmap.claims)


def test_disarmed_constructions_unchecked_even_after_instrumentation(
    monkeypatch,
):
    # arm, build (instruments the class), then disarm and build again:
    # the second instance must never be checked
    from kube_scheduler_simulator_tpu.utils.broker import CompileBroker

    monkeypatch.setenv(locking.RACE_ENV_VAR, "1")
    CompileBroker(speculative=False)
    monkeypatch.delenv(locking.RACE_ENV_VAR, raising=False)
    b2 = CompileBroker(speculative=False)
    assert b2.__dict__.get("_kss_guard_armed") is None
    b2._engines["fine"] = object()  # unarmed: no check, plain storage
    assert b2.peek("fine") is not None
