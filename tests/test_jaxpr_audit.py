"""The KSS7xx jaxpr auditor, runtime half (analysis/jaxpr_audit.py +
the utils/broker.jit hook, KSS_JAXPR_AUDIT=1).

The acceptance gate: a tier-1 chaos run of EVERY engine kind
(sequential + gang, sync + async pipelines) under the armed auditor
must produce zero findings — no host callbacks, no f64, every shape on
the bucket grid, donations consumed — and two identically-seeded runs
must produce IDENTICAL compile-fingerprint sets (recompile risk as an
assertion, not a bench postmortem). Negative tests hand the auditor
synthetic violating programs and require each rule to fire.
"""

import jax
import jax.numpy as jnp
import pytest

from kube_scheduler_simulator_tpu.analysis import jaxpr_audit
from kube_scheduler_simulator_tpu.analysis.jaxpr_audit import (
    AUDITOR,
    diff_fingerprints,
    load_fingerprints,
)
from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
from kube_scheduler_simulator_tpu.utils import broker as broker_mod

from helpers import node, pod


@pytest.fixture
def audit(monkeypatch):
    """Arm the auditor for engines built inside the test, over a clean
    registry; reset afterwards so records never leak across tests."""
    monkeypatch.setenv(jaxpr_audit.ENV_VAR, "1")
    AUDITOR.reset()
    yield AUDITOR
    AUDITOR.reset()


def rules_of(findings):
    return {f.rule for f in findings}


# -- the broker hook ----------------------------------------------------------


def test_hook_off_by_default(monkeypatch):
    monkeypatch.delenv(jaxpr_audit.ENV_VAR, raising=False)
    j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.off"})
    assert not isinstance(j, jaxpr_audit.AuditedJit)


def test_hook_audits_once_per_signature(audit):
    j = broker_mod.jit(lambda x: x * 2, audit={"label": "t.once"})
    assert isinstance(j, jaxpr_audit.AuditedJit)
    j(jnp.ones((8,), jnp.float32))
    j(jnp.zeros((8,), jnp.float32))  # same signature: no second record
    j(jnp.ones((16,), jnp.float32))  # new bucket: second record
    assert [r.label for r in AUDITOR.records] == ["t.once", "t.once"]
    assert AUDITOR.findings() == []


def test_eager_rung_bypasses_the_hook(audit):
    with broker_mod.eager_execution():
        f = broker_mod.jit(lambda x: x + 1, audit={"label": "t.eager"})
    assert not isinstance(f, jaxpr_audit.AuditedJit)


# -- negative tests: each runtime rule fires on a synthetic violation ---------


def test_callback_bearing_jaxpr_fires_kss711(audit):
    def f(x):
        jax.debug.print("x={x}", x=x)
        return x * 2

    j = broker_mod.jit(f, audit={"label": "t.callback"})
    j(jnp.ones((8,), jnp.float32))
    assert "KSS711" in rules_of(AUDITOR.findings())


def test_f64_leak_fires_kss712(audit):
    j = broker_mod.jit(
        lambda x: x.astype(jnp.float64), audit={"label": "t.f64"}
    )
    j(jnp.ones((8,), jnp.float32))
    (f,) = [f for f in AUDITOR.findings() if f.rule == "KSS712"]
    assert "float64" in f.message


def test_f64_waived_under_exact_policy(audit):
    j = broker_mod.jit(
        lambda x: x.astype(jnp.float64),
        audit={"label": "t.f64ok", "allow_f64": True},
    )
    j(jnp.ones((8,), jnp.float32))
    assert AUDITOR.findings() == []


def test_off_bucket_shape_fires_kss713(audit):
    j = broker_mod.jit(
        lambda x: x + 1,
        audit={"label": "t.bucket", "exempt": lambda a, k: ()},
    )
    j(jnp.ones((24,)))  # 24 > 8, not a power of two, not declared
    (f,) = [f for f in AUDITOR.findings() if f.rule == "KSS713"]
    assert "24" in f.message


def test_bucket_check_skipped_without_basis(audit):
    # no enc/exempt declared: the universal rules still run, the bucket
    # rule does not (the audit-spec contract, jaxpr_audit.py)
    j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.nobasis"})
    j(jnp.ones((24,), jnp.float32))
    assert AUDITOR.findings() == []


def test_declared_static_dims_pass_kss713(audit):
    j = broker_mod.jit(
        lambda x: x + 1,
        audit={
            "label": "t.static",
            "exempt": lambda a, k: (),
            "extra_dims": (24,),
        },
    )
    j(jnp.ones((24,), jnp.float32))
    assert AUDITOR.findings() == []


def test_dropped_donation_fires_kss714(audit):
    # the donated f32[8] can alias no output (shape+dtype change):
    # lowering warns, the auditor turns it into a finding
    j = broker_mod.jit(
        lambda x: x[:4].astype(jnp.int32),
        donate_argnums=(0,),
        audit={"label": "t.drop"},
    )
    j(jnp.ones((8,), jnp.float32))
    assert "KSS714" in rules_of(AUDITOR.findings())


def test_consumed_donation_is_clean(audit):
    j = broker_mod.jit(
        lambda x, y: x + y,
        donate_argnums=(0,),
        audit={"label": "t.keep"},
    )
    j(jnp.ones((8,), jnp.float32), jnp.ones((8,), jnp.float32))
    assert AUDITOR.findings() == []


def test_auditor_internal_failure_never_raises(audit):
    # the never-raise contract: a broken audit spec (here: a raising
    # exempt callable) must not crash the serving pass — it becomes a
    # KSS719 finding in the registry instead
    j = broker_mod.jit(
        lambda x: x + 1,
        audit={"label": "t.boom", "exempt": lambda a, k: 1 // 0},
    )
    out = j(jnp.ones((8,), jnp.float32))  # the call itself succeeds
    assert float(out[0]) == 2.0
    (f,) = AUDITOR.findings()
    assert f.rule == "KSS719"
    assert "ZeroDivisionError" in f.message


def test_fingerprint_drift_fires_kss715():
    old = {"seq.run": ["aaaa"], "gang.run": ["bbbb"]}
    new = {"seq.run": ["aaaa", "cccc"], "gang.run": ["bbbb"], "x": ["d"]}
    findings = diff_fingerprints(old, new)
    assert rules_of(findings) == {"KSS715"}
    (f,) = findings
    assert "seq.run" in f.message and "cccc" in f.message
    # a NEW label is growth, not drift
    assert not any("'x'" in g.message for g in findings)


def test_fingerprint_persist_round_trip(audit, tmp_path):
    j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.persist"})
    j(jnp.ones((8,), jnp.float32))
    path = str(tmp_path / "fp" / "kss-fingerprints.json")
    assert AUDITOR.persist(path) == []  # no baseline yet: no drift
    loaded = load_fingerprints(path)
    assert loaded == AUDITOR.fingerprints()
    # same programs again: persisting is drift-free
    assert AUDITOR.persist(path) == []
    # a changed digest for a known label IS drift
    mutated = {"t.persist": ["0" * 16]}
    assert rules_of(diff_fingerprints(loaded, mutated)) == {"KSS715"}


def test_load_rejects_foreign_documents(tmp_path):
    p = tmp_path / "kss-fingerprints.json"
    p.write_text('{"format": "something-else", "fingerprints": {"a": ["b"]}}')
    assert load_fingerprints(str(p)) == {}
    p.write_text("not json")
    assert load_fingerprints(str(p)) == {}


# -- the acceptance gate: chaos runs of every engine kind ---------------------


def _chaos(mode: str, pipeline: str, seed: int = 7) -> ChaosSpec:
    nodes = [node(f"n{i}", cpu="8", mem="16Gi", pods="110") for i in range(3)]
    pods = [pod(f"seed-{i}", cpu="200m", node_name=f"n{i % 3}") for i in range(5)]
    return ChaosSpec.from_dict(
        {
            "name": f"audit-{mode}-{pipeline}",
            "seed": seed,
            "horizon": 20.0,
            "schedulerMode": mode,
            "pipeline": pipeline,
            "snapshot": {"nodes": nodes, "pods": pods},
            "arrivals": [
                {
                    "kind": "poisson",
                    "rate": 0.5,
                    "count": 6,
                    "template": {
                        "metadata": {"name": "churn"},
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "resources": {
                                        "requests": {
                                            "cpu": "100m",
                                            "memory": "64Mi",
                                        }
                                    },
                                }
                            ]
                        },
                    },
                }
            ],
            "faults": [
                {"at": 8.0, "action": "fail", "node": "n1"},
                {"at": 14.0, "action": "recover", "node": "n1"},
            ],
        }
    )


def test_chaos_run_audits_every_engine_kind_clean(audit):
    # sequential + gang, sync + async: every program every engine kind
    # builds is traced and audited — and comes back clean (the KSS7xx
    # acceptance criterion: zero callbacks, zero f64, bucket-aligned
    # shapes, donations consumed)
    for mode in ("sequential", "gang"):
        for pipeline in ("sync", "async"):
            result = LifecycleEngine(_chaos(mode, pipeline)).run()
            assert result["phase"] == "Succeeded", (mode, pipeline, result)
    labels = AUDITOR.labels()
    assert "seq.run" in labels, labels
    assert any(lb.startswith("gang.") for lb in labels), labels
    assert AUDITOR.records, "nothing audited"
    bad = AUDITOR.findings()
    assert bad == [], "\n" + "\n".join(f.render() for f in bad)


def test_fingerprints_deterministic_across_identical_runs(audit):
    # two identically-seeded runs must compile-fingerprint identically:
    # a difference means a supposedly-deterministic churn run lowered a
    # DIFFERENT program set — exactly the recompile-risk regression the
    # auditor exists to catch
    LifecycleEngine(_chaos("sequential", "sync")).run()
    first = AUDITOR.fingerprints()
    AUDITOR.reset()
    LifecycleEngine(_chaos("sequential", "sync")).run()
    second = AUDITOR.fingerprints()
    assert first == second
    assert diff_fingerprints(first, second) == []
    assert first, "no fingerprints recorded"
