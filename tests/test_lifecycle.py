"""Cluster-lifecycle chaos engine (lifecycle/): ChaosSpec schema,
discrete-event determinism (byte-identical traces), eviction →
reschedule round trips, disruption metrics, the encoding cache, and the
HTTP surface (POST /api/v1/lifecycle + GET /api/v1/lifecycle/trace)."""

import json
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import SimulatorService

from helpers import node, pod


def _tmpl(name="web", cpu="500m"):
    return {
        "metadata": {"name": name},
        "spec": {
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": "128Mi"}}}
            ]
        },
    }


def _snapshot(n_nodes=3, cpu="4", pods=()):
    return {
        "nodes": [node(f"n{i}", cpu=cpu) for i in range(n_nodes)],
        "pods": list(pods),
    }


def _spec(**over):
    base = {
        "seed": 3,
        "horizon": 20,
        "schedulerMode": "sequential",
        "snapshot": _snapshot(),
        "arrivals": [
            {"kind": "poisson", "rate": 0.6, "count": 6, "template": _tmpl()}
        ],
        "faults": [
            {"at": 8.0, "action": "fail", "node": "n1"},
            {"at": 15.0, "action": "recover", "node": "n1"},
        ],
    }
    base.update(over)
    return ChaosSpec.from_dict(base)


class TestChaosSpecSchema:
    def test_strict_parse_errors(self):
        with pytest.raises(ValueError, match="unknown action"):
            ChaosSpec.from_dict(
                {"faults": [{"at": 1, "action": "explode", "node": "n0"}]}
            )
        with pytest.raises(ValueError, match="node"):
            ChaosSpec.from_dict({"faults": [{"at": 1, "action": "fail"}]})
        with pytest.raises(ValueError, match="taint"):
            ChaosSpec.from_dict(
                {"faults": [{"at": 1, "action": "taint", "node": "n0"}]}
            )
        with pytest.raises(ValueError, match="rate"):
            ChaosSpec.from_dict(
                {"arrivals": [{"kind": "poisson", "count": 3, "template": _tmpl()}]}
            )
        with pytest.raises(ValueError, match="metadata.name"):
            ChaosSpec.from_dict(
                {"arrivals": [{"kind": "poisson", "rate": 1, "count": 3,
                               "template": {"spec": {}}}]}
            )
        with pytest.raises(ValueError, match="unknown kind"):
            ChaosSpec.from_dict(
                {"arrivals": [{"kind": "burst", "template": _tmpl()}]}
            )
        with pytest.raises(ValueError, match="neither"):
            ChaosSpec.from_dict({"seed": 1})
        with pytest.raises(ValueError, match="share pod-name prefixes"):
            ChaosSpec.from_dict(
                {"arrivals": [
                    {"kind": "poisson", "rate": 1, "count": 2, "template": _tmpl("web")},
                    {"kind": "gang", "at": 1.0, "replicas": 2, "template": _tmpl("web")},
                ]}
            )
        with pytest.raises(ValueError, match="horizon"):
            ChaosSpec.from_dict({"horizon": 0, "faults": [
                {"at": 1, "action": "fail", "node": "n0"}]})

    def test_event_derivation_is_deterministic_and_horizon_capped(self):
        spec = _spec()
        e1, e2 = spec.events(), spec.events()
        assert e1 == e2
        assert all(t <= spec.horizon for t, *_ in e1)
        arrivals = [e for e in e1 if e[2] == "arrival"]
        assert 1 <= len(arrivals) <= 6  # count cap
        # sorted by time
        assert [e[0] for e in e1] == sorted(e[0] for e in e1)
        # a different seed reshuffles the poisson draws
        other = _spec(seed=4).events()
        assert [e[0] for e in other] != [e[0] for e in e1]

    def test_gang_arrival_is_one_batch(self):
        spec = _spec(
            arrivals=[{"kind": "gang", "at": 2.0, "replicas": 3,
                       "template": _tmpl("job")}],
            faults=[],
        )
        evs = spec.events()
        assert len(evs) == 1
        t, _, kind, payload = evs[0]
        assert (t, kind, payload["job"]) == (2.0, "arrival", "job")
        names = [p["metadata"]["name"] for p in payload["pods"]]
        assert names == ["job-0", "job-1", "job-2"]


class TestLifecycleEngine:
    def test_seeded_determinism_byte_identical_trace(self):
        a = LifecycleEngine(_spec())
        b = LifecycleEngine(_spec())
        ra, rb = a.run(), b.run()
        assert ra["phase"] == rb["phase"] == "Succeeded"
        assert a.trace_jsonl() == b.trace_jsonl()
        assert a.trace_jsonl()  # non-empty

    def test_eviction_reschedule_round_trip(self):
        # pods pinned by capacity: 2 nodes, each half full; failing one
        # moves its pods to the survivor
        snap = _snapshot(
            n_nodes=2, cpu="4",
            pods=[pod("a0", cpu="1", node_name=None), pod("a1", cpu="1")],
        )
        spec = _spec(
            snapshot=snap,
            arrivals=[{"kind": "trace", "times": [1.0], "template": _tmpl("late", cpu="1")}],
            faults=[{"at": 5.0, "action": "fail", "node": "n0"}],
        )
        eng = LifecycleEngine(spec)
        res = eng.run()
        assert res["phase"] == "Succeeded"
        evictions = [e for e in eng.trace if e["type"] == "Eviction"]
        fail = next(e for e in eng.trace if e["type"] == "NodeFail")
        assert fail["evicted"] == len(evictions)
        # the acceptance invariant: every evicted pod is re-scheduled or
        # reported unschedulable — never silently dropped
        rescheduled = {
            p
            for e in eng.trace
            if e["type"] == "SchedulingPass"
            for p in e["rescheduled"]
        }
        end = eng.trace[-1]
        assert end["type"] == "End"
        lost = {e["pod"] for e in eng.trace if e["type"] == "EvictedPodLost"}
        for e in evictions:
            assert (
                e["pod"] in rescheduled
                or e["pod"] in end["unschedulableEvicted"]
                or e["pod"] in lost
            ), e
        # this cluster has capacity: everything re-bound, onto n1 only
        assert end["unschedulableEvicted"] == []
        assert res["pods"]["evicted"] == len(evictions) > 0
        assert res["pods"]["rescheduled"] == res["pods"]["evicted"]
        for p in eng.store.list("pods"):
            assert p["spec"].get("nodeName") == "n1"

    def test_stranded_until_recover_measures_time_to_reschedule(self):
        # ONE schedulable node, sized to exactly its bound pods; the
        # other node's pods cannot re-place until the failed node
        # recovers at t=12 — time-to-reschedule must be 12 - 4 = 8
        snap = _snapshot(n_nodes=2, cpu="2", pods=[
            pod("a0", cpu="2", node_name="n0"),
            pod("a1", cpu="2", node_name="n1"),
        ])
        spec = _spec(
            snapshot=snap,
            arrivals=[{"kind": "trace", "times": [1.0],
                       "template": _tmpl("noise", cpu="4")}],  # never fits
            faults=[
                {"at": 4.0, "action": "fail", "node": "n0"},
                {"at": 12.0, "action": "recover", "node": "n0"},
            ],
        )
        eng = LifecycleEngine(spec)
        res = eng.run()
        assert res["phase"] == "Succeeded"
        assert res["pods"]["evicted"] == 1
        assert res["pods"]["rescheduled"] == 1
        assert res["timeToReschedule"]["count"] == 1
        assert res["timeToReschedule"]["meanS"] == pytest.approx(8.0)
        snap_metrics = eng.scheduler.metrics.snapshot()["disruption"]
        assert snap_metrics["evicted"] == 1
        assert snap_metrics["rescheduled"] == 1
        assert snap_metrics["meanTimeToRescheduleS"] == pytest.approx(8.0)

    def test_drain_and_cordon_respected(self):
        snap = _snapshot(n_nodes=2, cpu="4", pods=[pod("a0", cpu="1", node_name="n0")])
        spec = _spec(
            snapshot=snap,
            arrivals=[{"kind": "trace", "times": [6.0],
                       "template": _tmpl("late", cpu="1")}],
            faults=[{"at": 2.0, "action": "drain", "node": "n0"}],
        )
        eng = LifecycleEngine(spec)
        assert eng.run()["phase"] == "Succeeded"
        # drained node keeps existing but takes no pods; a0 moved to n1,
        # the later arrival avoids n0 too
        assert eng.store.get("nodes", "n0")["spec"]["unschedulable"] is True
        for p in eng.store.list("pods"):
            assert p["spec"].get("nodeName") == "n1", p["metadata"]["name"]

    def test_gang_mode_runs_the_timeline(self):
        spec = _spec(schedulerMode="gang")
        eng = LifecycleEngine(spec)
        res = eng.run()
        assert res["phase"] == "Succeeded"
        assert any(e["mode"] == "gang" for e in eng.trace
                   if e["type"] == "SchedulingPass")


class TestEncodingCache:
    def test_unchanged_store_reuses_encoding(self):
        svc = SimulatorService()
        svc.store.apply("nodes", node("n0"))
        svc.store.apply("pods", pod("p0"))
        cfg = svc.scheduler.config
        enc1 = svc.scheduler._encode_current(cfg)
        enc2 = svc.scheduler._encode_current(cfg)
        assert enc1 is enc2  # cache hit: same object, no re-encode
        svc.store.apply("pods", pod("p1"))
        enc3 = svc.scheduler._encode_current(cfg)
        assert enc3 is not enc2  # any mutation invalidates
        # a config swap invalidates even at the same resourceVersion
        svc.scheduler.restart(cfg.to_dict())
        assert svc.scheduler._encode_current(svc.scheduler.config) is not enc3

    def test_none_result_is_cacheable(self):
        svc = SimulatorService()
        cfg = svc.scheduler.config
        assert svc.scheduler._encode_current(cfg) is None
        assert svc.scheduler._encode_current(cfg) is None

    def test_schedule_results_unaffected(self):
        svc = SimulatorService()
        svc.store.apply("nodes", node("n0"))
        svc.store.apply("pods", pod("p0"))
        first = svc.scheduler.schedule()
        assert [r.status for r in first] == ["Scheduled"]
        # write-backs bumped the rv; a fresh pod schedules correctly
        svc.store.apply("pods", pod("p1"))
        second = svc.scheduler.schedule()
        assert [r.pod_name for r in second] == ["p1"]
        assert second[0].status == "Scheduled"


class TestLifecycleRoutes:
    def setup_method(self):
        self.server = SimulatorServer(SimulatorService(), port=0).start()
        self.base = f"http://127.0.0.1:{self.server.port}/api/v1"

    def teardown_method(self):
        self.server.shutdown()

    def _post(self, payload):
        req = urllib.request.Request(
            f"{self.base}/lifecycle",
            data=json.dumps(payload).encode(),
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())

    def test_end_to_end_chaos_run(self):
        # the acceptance-criteria spec: seeded, >= 1 node failure, a
        # Poisson arrival process, end-to-end over HTTP
        spec = {
            "seed": 11,
            "horizon": 16,
            "schedulerMode": "gang",
            "snapshot": _snapshot(
                n_nodes=2, cpu="4",
                pods=[pod("base0", cpu="1"), pod("base1", cpu="1")],
            ),
            "arrivals": [
                {"kind": "poisson", "rate": 0.4, "count": 4, "template": _tmpl()}
            ],
            "faults": [{"at": 6.0, "action": "fail", "node": "n0"}],
        }
        st, out = self._post(spec)
        assert st == 200
        assert out["phase"] == "Succeeded"
        trace = out["trace"]
        evictions = [e for e in trace if e["type"] == "Eviction"]
        rescheduled = {
            p
            for e in trace
            if e["type"] == "SchedulingPass"
            for p in e["rescheduled"]
        }
        lost = {e["pod"] for e in trace if e["type"] == "EvictedPodLost"}
        end = trace[-1]
        for e in evictions:
            assert (
                e["pod"] in rescheduled
                or e["pod"] in end["unschedulableEvicted"]
                or e["pod"] in lost
            ), e
        # isolation: the serving store saw none of it
        with urllib.request.urlopen(f"{self.base}/resources/pods") as resp:
            assert json.load(resp)["items"] == []
        # the run's passes + disruption flowed into the server's metrics
        with urllib.request.urlopen(f"{self.base}/metrics") as resp:
            m = json.load(resp)
        assert m["passes"] > 0
        assert m["disruption"]["evicted"] == len(evictions)

        # GET /lifecycle/trace replays the same events as JSONL
        with urllib.request.urlopen(f"{self.base}/lifecycle/trace") as resp:
            assert resp.headers["Content-Type"] == "application/x-ndjson"
            lines = resp.read().decode().splitlines()
        assert [json.loads(x) for x in lines] == trace

    def test_trace_404_before_any_run(self):
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{self.base}/lifecycle/trace")
        assert ei.value.code == 404

    def test_bad_spec_is_400(self):
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post({"faults": [{"at": 1, "action": "explode", "node": "x"}]})
        assert ei.value.code == 400
