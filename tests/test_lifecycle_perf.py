"""Churn-run performance wiring: the acceptance-criteria assertions for
the incremental encoding PR.

A lifecycle run with STABLE bucket occupancy (pod count stays inside one
capacity bucket, pending queue inside one queue bucket) must, after the
warm-up pass:

  * never re-compile — exactly one engine build for the whole timeline
    (`phases.engineBuilds`), and
  * never full-re-encode — exactly one full encode (the cold start),
    every later pass served by the delta path (`phases.deltaEncodes`).

Also covers the phase-timing breakdown plumbing end-to-end (service →
SchedulingMetrics → lifecycle result → metrics API shape).
"""

from __future__ import annotations

from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec

from helpers import node, pod


def _churn_spec(mode: str, *, n_nodes=6, seed_pods=33, arrivals=18) -> ChaosSpec:
    nodes = [node(f"n{i}", cpu="32", mem="64Gi", pods="110") for i in range(n_nodes)]
    # pre-bound pods hold the pod count inside ONE capacity bucket for the
    # whole run: the first encode sees 34 pods → bucket 64, and
    # 33 + 18 arrivals = 51 ≤ 64 — no crossing, so the cold start is the
    # only full encode and the only compile
    pods = [
        pod(f"seed-{i}", cpu="100m", node_name=f"n{i % n_nodes}")
        for i in range(seed_pods)
    ]
    return ChaosSpec.from_dict(
        {
            "name": f"churn-{mode}",
            "seed": 11,
            "horizon": 60.0,
            "schedulerMode": mode,
            "snapshot": {"nodes": nodes, "pods": pods},
            "arrivals": [
                {
                    "kind": "poisson",
                    "rate": 0.8,
                    "count": arrivals,
                    "template": {
                        "metadata": {"name": "churn"},
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "resources": {
                                        "requests": {
                                            "cpu": "100m",
                                            "memory": "64Mi",
                                        }
                                    },
                                }
                            ]
                        },
                    },
                }
            ],
        }
    )


def _run(mode: str):
    eng = LifecycleEngine(_churn_spec(mode))
    res = eng.run()
    assert res["phase"] == "Succeeded", res
    return eng, res


class TestWarmChurnIsIncremental:
    def test_gang_zero_recompiles_zero_full_reencodes_after_warmup(self):
        eng, res = _run("gang")
        phases = res["metrics"]["phases"]
        # the cold start is the ONLY full encode and the ONLY build
        assert phases["fullEncodes"] == 1, phases
        assert phases["engineBuilds"] == 1, phases
        # and the delta path actually carried the run
        assert phases["deltaEncodes"] >= 10, phases
        # every arrival got scheduled (the run did real work)
        assert res["pods"]["arrived"] >= 10
        pending = [
            p
            for p in eng.store.list("pods")
            if not (p.get("spec") or {}).get("nodeName")
        ]
        assert not pending

    def test_sequential_zero_recompiles_zero_full_reencodes_after_warmup(self):
        eng, res = _run("sequential")
        phases = res["metrics"]["phases"]
        assert phases["fullEncodes"] == 1, phases
        # the sequential scan bakes the BUCKETED queue length; a small
        # steady churn stays in the lowest bucket → one build
        assert phases["engineBuilds"] == 1, phases
        assert phases["deltaEncodes"] >= 10, phases

    def test_phase_seconds_populated(self):
        _, res = _run("gang")
        phases = res["metrics"]["phases"]
        assert phases["encodeSeconds"] > 0
        assert phases["compileSeconds"] >= 0
        assert phases["executeSeconds"] > 0
        assert phases["decodeSeconds"] >= 0

    def test_timings_carry_encode_mode(self):
        eng, _ = _run("gang")
        modes = {t.get("encodeMode") for t in eng.timings}
        assert "delta" in modes, eng.timings
        # the trace itself stays deterministic: no encode mode leaks in
        assert not any("encodeMode" in e for e in eng.trace)


class TestMetricsApiShape:
    def test_snapshot_exposes_phdi_block(self):
        from kube_scheduler_simulator_tpu.utils.metrics import SchedulingMetrics

        m = SchedulingMetrics()
        m.record_encode("full", 0.25)
        m.record_encode("delta", 0.01)
        m.record_encode("cached", 0.0)
        m.record_encode("empty", 0.0)
        m.record_engine_build(1.5)
        m.record_phase_seconds(execute=0.5, decode=0.125)
        m.record_compile(hits=3, misses=1, speculative=2, stall_s=4.5)
        snap = m.snapshot()["phases"]
        assert snap["fullEncodes"] == 1
        assert snap["deltaEncodes"] == 1
        assert snap["cachedEncodes"] == 1
        assert snap["emptyEncodes"] == 1
        assert snap["engineBuilds"] == 1
        assert snap["encodeSeconds"] == 0.26
        assert snap["compileSeconds"] == 1.5
        assert snap["executeSeconds"] == 0.5
        assert snap["decodeSeconds"] == 0.125
        assert snap["compileHits"] == 3
        assert snap["compileMisses"] == 1
        assert snap["speculativeCompiles"] == 2
        assert snap["stallSeconds"] == 4.5
        m.reset()
        snap = m.snapshot()["phases"]
        assert snap["fullEncodes"] == 0 and snap["encodeSeconds"] == 0.0
        assert snap["compileMisses"] == 0 and snap["stallSeconds"] == 0.0

    def test_http_metrics_route_carries_phases(self):
        import json
        import urllib.request

        from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
        from kube_scheduler_simulator_tpu.server.service import SimulatorService

        server = SimulatorServer(SimulatorService(), port=0).start()
        try:
            svc = server.service
            svc.store.apply("nodes", node("n0"))
            svc.store.apply("pods", pod("p0"))
            svc.scheduler.schedule()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/api/v1/metrics"
            ) as resp:
                snap = json.loads(resp.read())
            assert "phases" in snap
            assert snap["phases"]["fullEncodes"] >= 1
            assert snap["phases"]["encodeSeconds"] > 0
        finally:
            server.shutdown()
