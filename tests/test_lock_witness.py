"""The runtime lock-order witness (utils/locking.py, KSS_LOCK_CHECK=1).

Unit half: the witness's own semantics — inversion detection (including
transitive cycles), RLock re-entrancy, non-LIFO release, zero wrapping
when the switch is off.

Integration half: the PR 6 session plane's concurrency — create / fork
/ evict / restore / delete / schedule racing across threads — runs
under the witness with ZERO order inversions. This is the regression
net for the bulkheads: a future PR that takes the manager lock inside a
session state lock (or the schedule lock inside the broker lock) fails
HERE, with both sites named, instead of deadlocking a production
replica once a year.
"""

import threading

import pytest

from kube_scheduler_simulator_tpu.utils import locking
from kube_scheduler_simulator_tpu.utils.locking import (
    LockOrderInversion,
    LockWitness,
    WitnessLock,
    WitnessRLock,
)


# -- unit: the witness itself -------------------------------------------------


def test_inversion_raises_with_both_sites():
    w = LockWitness()
    a = WitnessLock("role.a", w)
    b = WitnessLock("role.b", w)
    with a:
        with b:
            pass
    with pytest.raises(LockOrderInversion) as ei:
        with b:
            with a:
                pass
    msg = str(ei.value)
    assert "role.a" in msg and "role.b" in msg
    assert len(w.inversions) == 1
    # the raise released the underlying lock: a is re-acquirable
    with a:
        pass


def test_transitive_cycle_detected():
    w = LockWitness()
    a, b, c = (WitnessLock(r, w) for r in ("t.a", "t.b", "t.c"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(LockOrderInversion):
        with c:
            with a:
                pass


def test_rlock_reentrancy_records_once():
    w = LockWitness()
    a = WitnessRLock("r.a", w)
    with a:
        with a:  # re-entrant: no self-edge, no double count
            pass
    assert w.snapshot()["acquisitions"] == 1
    assert w.snapshot()["edges"] == {}


def test_non_lifo_release_keeps_held_set_straight():
    w = LockWitness()
    a = WitnessLock("n.a", w)
    b = WitnessLock("n.b", w)
    a.acquire()
    b.acquire()
    a.release()  # release out of order
    # only b is held now: acquiring a fresh lock must edge from b only
    c = WitnessLock("n.c", w)
    c.acquire()
    c.release()
    b.release()
    assert set(w.snapshot()["edges"]) == {"n.a -> n.b", "n.b -> n.c"}


def test_cross_thread_release_keeps_witness_straight():
    # a plain Lock may be released by a different thread than its
    # acquirer (SchedulingPassHandle's dispatch->resolve shape): the
    # acquirer's held set must be cleaned up, not silently leaked into
    # phantom edges/inversions
    w = LockWitness()
    a = WitnessLock("x.pass", w)
    b = WitnessLock("x.other", w)
    a.acquire()  # main thread acquires

    done = []

    def releaser():
        a.release()  # other thread releases
        done.append(True)

    th = threading.Thread(target=releaser)
    th.start()
    th.join(timeout=5)
    assert done
    # main thread no longer holds x.pass: acquiring b records no edge,
    # and the reverse order later is NOT an inversion
    with b:
        pass
    with b:
        a.acquire()
        a.release()
    assert set(w.snapshot()["edges"]) == {"x.other -> x.pass"}
    assert w.snapshot()["inversions"] == []


def test_same_role_never_edges():
    # roles name lock CLASSES (every broker lease shares one role); two
    # instances of a role cannot be ordered by name, so no self-edges
    # and no false inversions between them
    w = LockWitness()
    a1 = WitnessLock("lease", w)
    a2 = WitnessLock("lease", w)
    with a1:
        with a2:
            pass
    with a2:
        with a1:
            pass
    assert w.snapshot()["edges"] == {}
    assert w.snapshot()["inversions"] == []


def test_condition_over_witness_lock():
    # broker._idle is threading.Condition(self._lock): wait/notify must
    # flow through the wrapper's acquire/release unharmed
    w = LockWitness()
    lk = WitnessLock("cond.lock", w)
    cond = threading.Condition(lk)
    ready = []

    def waiter():
        with cond:
            while not ready:
                cond.wait(1.0)

    th = threading.Thread(target=waiter)
    th.start()
    with cond:
        ready.append(1)
        cond.notify_all()
    th.join(timeout=5)
    assert not th.is_alive()
    assert w.snapshot()["inversions"] == []


def test_factories_plain_when_disabled(monkeypatch):
    monkeypatch.delenv(locking.ENV_VAR, raising=False)
    assert isinstance(locking.make_lock("x"), type(threading.Lock()))
    monkeypatch.setenv(locking.ENV_VAR, "0")
    assert isinstance(locking.make_rlock("x"), type(threading.RLock()))
    monkeypatch.setenv(locking.ENV_VAR, "1")
    assert isinstance(locking.make_lock("x"), WitnessLock)
    assert isinstance(locking.make_rlock("x"), WitnessRLock)


def test_lock_check_registered_and_documented():
    # dogfood (ISSUE 7 satellite): the witness switch itself passes the
    # env-registry analyzer's three-way contract
    from kube_scheduler_simulator_tpu.utils import envcheck

    assert "KSS_LOCK_CHECK" in envcheck.KNOWN
    assert envcheck.check_env({"KSS_LOCK_CHECK": "1"}) == []
    assert envcheck.check_env({"KSS_LOCK_CHECK": "maybe"}) != []


# -- integration: concurrent session plane under the witness ------------------


def _cluster(n_nodes=3, n_pods=4):
    return {
        "nodes": [
            {
                "metadata": {"name": f"n{i}"},
                "status": {
                    "allocatable": {
                        "cpu": "8", "memory": "16Gi", "pods": "110"
                    }
                },
            }
            for i in range(n_nodes)
        ],
        "pods": [
            {
                "metadata": {"name": f"p{i}"},
                "spec": {
                    "containers": [
                        {"resources": {"requests": {"cpu": "500m"}}}
                    ]
                },
            }
            for i in range(n_pods)
        ],
    }


@pytest.fixture
def witness(monkeypatch):
    """Arm KSS_LOCK_CHECK for locks created inside the test, against a
    clean global graph; reset afterwards so edges never leak across
    tests."""
    monkeypatch.setenv(locking.ENV_VAR, "1")
    locking.WITNESS.reset()
    yield locking.WITNESS
    locking.WITNESS.reset()


@pytest.fixture
def race_witness(monkeypatch):
    """Arm BOTH witnesses — lock order and guarded state
    (KSS_RACE_CHECK=1) — for objects built inside the test: the session
    stress must hold zero inversions AND zero UnguardedAccess (the
    KSS6xx acceptance gate)."""
    monkeypatch.setenv(locking.ENV_VAR, "1")
    monkeypatch.setenv(locking.RACE_ENV_VAR, "1")
    locking.WITNESS.reset()
    yield locking.WITNESS
    locking.WITNESS.reset()


def test_concurrent_sessions_zero_inversions(witness):
    _run_session_stress(witness)


def test_concurrent_sessions_zero_unguarded_access(race_witness):
    # the KSS6xx runtime gate: the same 4-thread create/schedule/fork/
    # evict/restore/delete stress, with every inferred lock-claimed
    # attribute wrapped in a checking descriptor — an access with no
    # claiming lock held raises UnguardedAccess into `errors`
    _run_session_stress(race_witness)


def _run_session_stress(witness):
    from kube_scheduler_simulator_tpu.server.service import SimulatorService
    from kube_scheduler_simulator_tpu.server.sessions import (
        SessionBusy,
        SessionManager,
    )

    mgr = SessionManager(
        SimulatorService(),
        max_sessions=64,
        max_concurrent_passes=8,
        idle_evict_s=None,
    )
    errors: list = []
    barrier = threading.Barrier(4)

    def tenant(i: int) -> None:
        try:
            barrier.wait(timeout=10)
            for round_ in range(3):
                sess, errs = mgr.create(
                    name=f"t{i}-{round_}", snapshot=_cluster()
                )
                assert errs == []
                with mgr.using(sess.id) as s:
                    s.service.scheduler.schedule()
                fork = mgr.fork(sess.id)
                try:
                    mgr.evict(fork.id)
                except SessionBusy:
                    pass
                mgr.get(fork.id)  # restore (or plain touch)
                mgr.info(sess.id)
                mgr.list_info()
                mgr.stats()
                mgr.delete(fork.id)
                mgr.delete(sess.id)
        except BaseException as e:  # noqa: BLE001 — surfaced to the assert
            errors.append(e)

    threads = [
        threading.Thread(target=tenant, args=(i,), name=f"tenant-{i}")
        for i in range(4)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    assert not any(th.is_alive() for th in threads)
    mgr.shutdown()

    assert errors == [], errors  # a LockOrderInversion would land here
    snap = witness.snapshot()
    assert snap["inversions"] == []
    # the run must have actually exercised the instrumented stack: the
    # documented cross-layer orderings appear as recorded edges
    edges = set(snap["edges"])
    assert snap["acquisitions"] > 100
    assert "session.state -> sessions.manager" in edges
    assert any(e.startswith("service.schedule -> ") for e in edges)


def test_witness_sees_schedule_to_broker_ordering(witness):
    # the ordering the STATIC analyzer cannot see (cross-module call):
    # a pass holds the schedule lock, then the broker lock — recorded
    # by the witness as exactly that edge
    from kube_scheduler_simulator_tpu.server.service import SimulatorService

    svc = SimulatorService()
    errs = svc.import_(_cluster())
    assert errs == []
    svc.scheduler.schedule()
    edges = set(witness.snapshot()["edges"])
    assert "service.schedule -> broker.lock" in edges
    assert witness.snapshot()["inversions"] == []
