"""Scheduling metrics counters + the /api/v1/metrics route."""

import json
import urllib.request

from kube_scheduler_simulator_tpu.utils.metrics import (
    GLOBAL,
    PassRecord,
    SchedulingMetrics,
)

from helpers import node, pod


def test_counters_accumulate():
    m = SchedulingMetrics(keep=3)
    for i in range(5):
        m.record(PassRecord("sequential", pods=10, scheduled=9, wall_s=0.5))
    snap = m.snapshot()
    assert snap["passes"] == 5  # monotonic count
    assert len(snap["recent"]) == 3  # rolling window
    assert snap["totalPods"] == 50  # totals keep accumulating
    assert snap["totalScheduled"] == 45
    assert snap["decisionsPerSecond"] == 20.0
    assert snap["recent"][0]["decisionsPerSecond"] == 20.0
    m.reset()
    assert m.snapshot()["passes"] == 0


def test_time_pass_context():
    m = SchedulingMetrics()
    with m.time_pass("gang") as ctx:
        ctx.done(pods=7, scheduled=7, rounds=3)
    snap = m.snapshot()
    assert snap["recent"][0]["mode"] == "gang"
    assert snap["recent"][0]["rounds"] == 3
    assert snap["recent"][0]["wallSeconds"] > 0


def test_profile_trace_writes_artifact(tmp_path):
    """profile_trace captures a TensorBoard/XProf trace directory —
    the SURVEY §5 tracing artifact (bench.py --profile wraps the warm
    pass in this)."""
    import jax
    import jax.numpy as jnp

    from kube_scheduler_simulator_tpu.utils.metrics import profile_trace

    d = str(tmp_path / "trace")
    with profile_trace(d):
        jax.jit(lambda x: x * 2)(jnp.ones((8,))).block_until_ready()
    import os

    found = []
    for root, _, files in os.walk(d):
        found += files
    assert found, "profiler trace directory is empty"


def test_per_service_metrics_attributable():
    """Two services in one process must not interleave their pass
    counters (ADVICE r3): each SchedulerService owns its registry."""
    from kube_scheduler_simulator_tpu.server.service import SimulatorService

    a, b = SimulatorService(), SimulatorService()
    assert a.scheduler.metrics is not b.scheduler.metrics
    for obj, kind in [(node("n0"), "nodes"), (pod("p0"), "pods")]:
        a.store.apply(kind, obj)
    a.scheduler.schedule()
    assert a.scheduler.metrics.snapshot()["passes"] == 1
    assert b.scheduler.metrics.snapshot()["passes"] == 0


def test_schedule_pass_records_and_route_serves(tmp_path):
    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
    from kube_scheduler_simulator_tpu.server.service import SimulatorService

    GLOBAL.reset()
    server = SimulatorServer(SimulatorService(), port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}/api/v1"
        for obj, kind in [(node("n0"), "nodes"), (pod("p0"), "pods")]:
            req = urllib.request.Request(
                f"{base}/resources/{kind}",
                data=json.dumps(obj).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req)
        urllib.request.urlopen(
            urllib.request.Request(f"{base}/schedule", data=b"", method="POST")
        )
        with urllib.request.urlopen(f"{base}/metrics") as resp:
            snap = json.load(resp)
        assert snap["passes"] >= 1
        assert snap["totalScheduled"] >= 1
        assert snap["recent"][-1]["mode"] == "sequential"
    finally:
        server.shutdown()
