"""Scheduling metrics counters + the /api/v1/metrics route (JSON and
Prometheus exposition), and the latency histograms the observability
PR added to both."""

import json
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.utils.metrics import (
    GLOBAL,
    METRICS_SCHEMA_VERSION,
    Histogram,
    PassRecord,
    SchedulingMetrics,
    parse_prometheus_text,
    render_prometheus,
)

from helpers import node, pod


def test_counters_accumulate():
    m = SchedulingMetrics(keep=3)
    for i in range(5):
        m.record(PassRecord("sequential", pods=10, scheduled=9, wall_s=0.5))
    snap = m.snapshot()
    assert snap["passes"] == 5  # monotonic count
    assert len(snap["recent"]) == 3  # rolling window
    assert snap["totalPods"] == 50  # totals keep accumulating
    assert snap["totalScheduled"] == 45
    assert snap["decisionsPerSecond"] == 20.0
    assert snap["recent"][0]["decisionsPerSecond"] == 20.0
    m.reset()
    assert m.snapshot()["passes"] == 0


def test_time_pass_context():
    m = SchedulingMetrics()
    with m.time_pass("gang") as ctx:
        ctx.done(pods=7, scheduled=7, rounds=3)
    snap = m.snapshot()
    assert snap["recent"][0]["mode"] == "gang"
    assert snap["recent"][0]["rounds"] == 3
    assert snap["recent"][0]["wallSeconds"] > 0


def test_profile_trace_writes_artifact(tmp_path):
    """profile_trace captures a TensorBoard/XProf trace directory —
    the SURVEY §5 tracing artifact (bench.py --profile wraps the warm
    pass in this)."""
    import jax
    import jax.numpy as jnp

    from kube_scheduler_simulator_tpu.utils.metrics import profile_trace

    d = str(tmp_path / "trace")
    with profile_trace(d):
        jax.jit(lambda x: x * 2)(jnp.ones((8,))).block_until_ready()
    import os

    found = []
    for root, _, files in os.walk(d):
        found += files
    assert found, "profiler trace directory is empty"


def test_per_service_metrics_attributable():
    """Two services in one process must not interleave their pass
    counters (ADVICE r3): each SchedulerService owns its registry."""
    from kube_scheduler_simulator_tpu.server.service import SimulatorService

    a, b = SimulatorService(), SimulatorService()
    assert a.scheduler.metrics is not b.scheduler.metrics
    for obj, kind in [(node("n0"), "nodes"), (pod("p0"), "pods")]:
        a.store.apply(kind, obj)
    a.scheduler.schedule()
    assert a.scheduler.metrics.snapshot()["passes"] == 1
    assert b.scheduler.metrics.snapshot()["passes"] == 0


def test_schedule_pass_records_and_route_serves(tmp_path):
    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
    from kube_scheduler_simulator_tpu.server.service import SimulatorService

    GLOBAL.reset()
    server = SimulatorServer(SimulatorService(), port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}/api/v1"
        for obj, kind in [(node("n0"), "nodes"), (pod("p0"), "pods")]:
            req = urllib.request.Request(
                f"{base}/resources/{kind}",
                data=json.dumps(obj).encode(),
                method="POST",
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req)
        urllib.request.urlopen(
            urllib.request.Request(f"{base}/schedule", data=b"", method="POST")
        )
        with urllib.request.urlopen(f"{base}/metrics") as resp:
            snap = json.load(resp)
        assert snap["passes"] >= 1
        assert snap["totalScheduled"] >= 1
        assert snap["recent"][-1]["mode"] == "sequential"
        # same route, ?format=prometheus: exposition text that survives
        # a REAL text-format parse (not a substring check)
        with urllib.request.urlopen(f"{base}/metrics?format=prometheus") as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            families = parse_prometheus_text(resp.read().decode())
        assert families["kss_passes_total"]["samples"][0][2] >= 1
        assert families["kss_pass_latency_seconds"]["type"] == "histogram"
    finally:
        server.shutdown()


class TestHistogram:
    def test_cumulative_buckets_and_inf(self):
        h = Histogram(bounds=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()
        assert snap["buckets"] == {"0.1": 1, "1.0": 3, "10.0": 4, "+Inf": 5}
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(56.05)
        # a value exactly on a bound lands IN that bound's bucket (le=)
        h.observe(1.0)
        assert h.snapshot()["buckets"]["1.0"] == 4

    def test_bounds_must_increase(self):
        with pytest.raises(ValueError):
            Histogram(bounds=())
        with pytest.raises(ValueError):
            Histogram(bounds=(1.0, 1.0))
        with pytest.raises(ValueError):
            Histogram(bounds=(2.0, 1.0))

    def test_state_round_trip(self):
        h = Histogram(bounds=(0.5, 2.0))
        for v in (0.1, 1.0, 9.0):
            h.observe(v)
        restored = Histogram(bounds=(0.5, 2.0))
        restored.load_state(json.loads(json.dumps(h.state_dict())))
        assert restored.snapshot() == h.snapshot()

    def test_mismatched_bounds_ignored_not_loaded_wrong(self):
        h = Histogram(bounds=(0.5, 2.0))
        h.observe(1.0)
        other = Histogram(bounds=(0.25, 4.0))
        other.load_state(h.state_dict())
        assert other.count == 0  # stayed fresh rather than re-bucketed


class TestSnapshotSchema:
    def test_schema_version_and_uptime(self):
        m = SchedulingMetrics()
        snap = m.snapshot()
        assert snap["schemaVersion"] == METRICS_SCHEMA_VERSION
        assert snap["uptimeSeconds"] >= 0.0
        assert set(snap["histograms"]) == {
            "passLatencySeconds",
            "compileStallSeconds",
            "timeToRescheduleSeconds",
        }

    def test_recorders_feed_the_histograms(self):
        m = SchedulingMetrics()
        m.record(PassRecord("sequential", pods=4, scheduled=4, wall_s=0.02))
        m.record_compile(misses=1, stall_s=0.3)
        m.record_disruption(
            evicted=2, rescheduled=2, times_to_reschedule_s=[1.5, 40.0]
        )
        hists = m.snapshot()["histograms"]
        assert hists["passLatencySeconds"]["count"] == 1
        assert hists["compileStallSeconds"]["count"] == 1
        assert hists["timeToRescheduleSeconds"]["count"] == 2
        assert hists["timeToRescheduleSeconds"]["buckets"]["2.5"] == 1
        m.reset()
        assert m.snapshot()["histograms"]["passLatencySeconds"]["count"] == 0

    def test_state_dict_round_trips_histograms(self):
        m = SchedulingMetrics()
        m.record(PassRecord("gang", pods=8, scheduled=8, wall_s=0.004))
        m.record_disruption(times_to_reschedule_s=[7.0])
        fresh = SchedulingMetrics()
        fresh.load_state(json.loads(json.dumps(m.state_dict())))
        a, b = m.snapshot(), fresh.snapshot()
        assert a["histograms"] == b["histograms"]
        assert a["passes"] == b["passes"]
        # pre-telemetry checkpoint (no _histograms key): loads clean
        state = m.state_dict()
        state.pop("_histograms")
        legacy = SchedulingMetrics()
        legacy.load_state(state)
        assert legacy.snapshot()["passes"] == 1
        assert legacy.snapshot()["histograms"]["passLatencySeconds"]["count"] == 0


class TestPrometheusExposition:
    def test_render_survives_a_real_parse(self):
        m = SchedulingMetrics()
        m.record(PassRecord("sequential", pods=10, scheduled=9, wall_s=0.5))
        m.record_compile(hits=3, misses=1, stall_s=0.2)
        text = render_prometheus(
            m.snapshot(),
            extra_gauges={
                "kss_encoding_cache_capacity": ("Encoding cache slots.", 8)
            },
        )
        families = parse_prometheus_text(text)
        assert families["kss_passes_total"]["samples"] == [
            ("kss_passes_total", {}, 1.0)
        ]
        assert families["kss_encoding_cache_capacity"]["type"] == "gauge"
        modes = {
            labels["mode"]: v
            for _, labels, v in families["kss_encodes_total"]["samples"]
        }
        assert set(modes) == {"delta", "full", "cached", "empty"}
        hist = families["kss_pass_latency_seconds"]
        assert hist["type"] == "histogram"
        inf = [
            v
            for name, labels, v in hist["samples"]
            if labels.get("le") == "+Inf"
        ]
        assert inf == [1.0]

    def test_parser_rejects_malformed(self):
        with pytest.raises(ValueError, match="no preceding TYPE"):
            parse_prometheus_text("kss_mystery_total 3\n")
        with pytest.raises(ValueError, match="duplicate TYPE"):
            parse_prometheus_text(
                "# TYPE kss_x counter\n# TYPE kss_x counter\nkss_x 1\n"
            )
        with pytest.raises(ValueError, match="unparseable"):
            parse_prometheus_text("# TYPE kss_x counter\nkss_x one\n")
        with pytest.raises(ValueError, match="non-monotonic"):
            parse_prometheus_text(
                "# TYPE kss_h histogram\n"
                'kss_h_bucket{le="1.0"} 5\n'
                'kss_h_bucket{le="+Inf"} 3\n'
                "kss_h_sum 1\nkss_h_count 3\n"
            )
        with pytest.raises(ValueError, match="!= _count"):
            parse_prometheus_text(
                "# TYPE kss_h histogram\n"
                'kss_h_bucket{le="1.0"} 2\n'
                'kss_h_bucket{le="+Inf"} 3\n'
                "kss_h_sum 1\nkss_h_count 4\n"
            )
