import json

from kube_scheduler_simulator_tpu.sched.oracle import Oracle
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration

from helpers import node, pod


def schedule(nodes, pods, **kw):
    o = Oracle(nodes, pods, **kw)
    return o, o.schedule_all()


def test_basic_fit_lands_on_free_node():
    nodes = [node("n0", cpu="1"), node("n1", cpu="4")]
    # n0 already can't fit a 2-cpu pod
    o, results = schedule(nodes, [pod("p0", cpu="2")])
    assert results[0].status == "Scheduled"
    assert results[0].selected_node == "n1"
    assert results[0].filter["n0"]["NodeResourcesFit"] == "Insufficient cpu"
    assert results[0].filter["n1"]["NodeResourcesFit"] == "passed"


def test_too_many_pods():
    nodes = [node("n0", pods="0"), node("n1")]
    o, results = schedule(nodes, [pod("p0")])
    assert results[0].selected_node == "n1"
    assert results[0].filter["n0"]["NodeResourcesFit"] == "Too many pods"


def test_least_allocated_prefers_empty_node():
    nodes = [node("n0", cpu="4", mem="8Gi"), node("n1", cpu="4", mem="8Gi")]
    existing = pod("busy", cpu="3", mem="6Gi", node_name="n0")
    o, results = schedule(nodes, [existing, pod("p0", cpu="100m")])
    assert results[0].selected_node == "n1"


def test_sequential_capacity_updates():
    # two 2-cpu pods, two 3-cpu nodes: second pod must go to the other node
    nodes = [node("n0", cpu="3", mem="8Gi"), node("n1", cpu="3", mem="8Gi")]
    o, results = schedule(nodes, [pod("a", cpu="2", mem="1Gi"), pod("b", cpu="2", mem="1Gi")])
    assert {results[0].selected_node, results[1].selected_node} == {"n0", "n1"}


def test_node_name_filter():
    nodes = [node("n0"), node("n1")]
    p = pod("p0")
    p["spec"]["nodeName"] = ""  # unset
    o, results = schedule(nodes, [pod("p0", node_selector=None)])
    assert results[0].status == "Scheduled"
    # pinned pod: nodeName set but pod still pending (not counted as bound
    # because node doesn't exist in snapshot? use existing node)


def test_unschedulable_node():
    nodes = [node("n0", unschedulable=True), node("n1")]
    o, results = schedule(nodes, [pod("p0")])
    assert results[0].selected_node == "n1"
    assert results[0].filter["n0"]["NodeUnschedulable"] == "node(s) were unschedulable"
    # with toleration it can land on n0 too (but scoring still picks a node)
    tol = [{"key": "node.kubernetes.io/unschedulable", "operator": "Exists", "effect": "NoSchedule"}]
    o2, results2 = schedule(nodes, [pod("p1", tolerations=tol)])
    assert results2[0].filter["n0"]["NodeUnschedulable"] == "passed"


def test_taint_toleration_filter_and_score():
    taint = [{"key": "dedicated", "value": "gpu", "effect": "NoSchedule"}]
    pref = [{"key": "noisy", "value": "true", "effect": "PreferNoSchedule"}]
    nodes = [node("n0", taints=taint), node("n1", taints=pref), node("n2")]
    o, results = schedule(nodes, [pod("p0")])
    r = results[0]
    assert "untolerated taint" in r.filter["n0"]["TaintToleration"]
    # n1 passes filter but scores worse than n2 on TaintToleration
    assert r.filter["n1"]["TaintToleration"] == "passed"
    assert int(r.final_score["n1"]["TaintToleration"]) < int(r.final_score["n2"]["TaintToleration"])
    assert r.selected_node == "n2"

    tol = [{"key": "dedicated", "operator": "Equal", "value": "gpu", "effect": "NoSchedule"}]
    o2, results2 = schedule(nodes, [pod("p1", tolerations=tol)])
    assert results2[0].filter["n0"]["TaintToleration"] == "passed"


def test_node_selector_and_affinity():
    nodes = [node("n0", labels={"disk": "hdd"}), node("n1", labels={"disk": "ssd"})]
    o, results = schedule(nodes, [pod("p0", node_selector={"disk": "ssd"})])
    assert results[0].selected_node == "n1"
    assert "affinity" in results[0].filter["n0"]["NodeAffinity"]

    aff = {
        "nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [
                    {"matchExpressions": [{"key": "disk", "operator": "In", "values": ["ssd"]}]}
                ]
            }
        }
    }
    o2, results2 = schedule(nodes, [pod("p1", affinity=aff)])
    assert results2[0].selected_node == "n1"


def test_node_affinity_preferred_scoring():
    nodes = [node("n0", labels={"zone": "a"}), node("n1", labels={"zone": "b"})]
    aff = {
        "nodeAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {
                    "weight": 10,
                    "preference": {
                        "matchExpressions": [{"key": "zone", "operator": "In", "values": ["b"]}]
                    },
                }
            ]
        }
    }
    o, results = schedule(nodes, [pod("p0", affinity=aff)])
    assert results[0].selected_node == "n1"
    assert int(results[0].final_score["n1"]["NodeAffinity"]) == 100
    assert int(results[0].final_score["n0"]["NodeAffinity"]) == 0


def test_node_ports_conflict():
    ports = [{"containerPort": 80, "hostPort": 8080}]
    nodes = [node("n0"), node("n1")]
    existing = pod("web", ports=ports, node_name="n0")
    o, results = schedule(nodes, [existing, pod("p0", ports=ports)])
    r = results[0]
    assert "free ports" in r.filter["n0"]["NodePorts"]
    assert r.selected_node == "n1"


def test_topology_spread_filter():
    # 2 zones; zone a already has 2 matching pods, zone b has 0; maxSkew 1
    nodes = [
        node("n0", labels={"topology.kubernetes.io/zone": "a", "kubernetes.io/hostname": "n0"}),
        node("n1", labels={"topology.kubernetes.io/zone": "b", "kubernetes.io/hostname": "n1"}),
    ]
    spread = [
        {
            "maxSkew": 1,
            "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "web"}},
        }
    ]
    existing = [
        pod("w1", labels={"app": "web"}, node_name="n0"),
        pod("w2", labels={"app": "web"}, node_name="n0"),
    ]
    new = pod("w3", labels={"app": "web"}, spread=spread)
    o, results = schedule(nodes, existing + [new])
    r = results[0]
    assert "topology spread" in r.filter["n0"]["PodTopologySpread"]
    assert r.selected_node == "n1"


def test_interpod_anti_affinity():
    nodes = [
        node("n0", labels={"kubernetes.io/hostname": "n0"}),
        node("n1", labels={"kubernetes.io/hostname": "n1"}),
    ]
    anti = {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "db"}},
                    "topologyKey": "kubernetes.io/hostname",
                }
            ]
        }
    }
    existing = pod("db-0", labels={"app": "db"}, node_name="n0")
    new = pod("db-1", labels={"app": "db"}, affinity=anti)
    o, results = schedule(nodes, [existing, new])
    r = results[0]
    assert "anti-affinity" in r.filter["n0"]["InterPodAffinity"]
    assert r.selected_node == "n1"


def test_interpod_required_affinity_and_first_pod_rule():
    nodes = [
        node("n0", labels={"kubernetes.io/hostname": "n0"}),
        node("n1", labels={"kubernetes.io/hostname": "n1"}),
    ]
    aff = {
        "podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": "kubernetes.io/hostname",
                }
            ]
        }
    }
    # first pod matching its own selector: allowed anywhere
    first = pod("web-0", labels={"app": "web"}, affinity=aff)
    o, results = schedule(nodes, [first])
    assert results[0].status == "Scheduled"

    # second pod must co-locate with web-0
    existing = pod("web-0", labels={"app": "web"}, node_name="n1")
    second = pod("web-1", labels={"app": "web"}, affinity=aff)
    o2, results2 = schedule(nodes, [existing, second])
    assert results2[0].selected_node == "n1"
    assert "affinity rules" in results2[0].filter["n0"]["InterPodAffinity"]


def test_existing_pod_anti_affinity_symmetry():
    nodes = [
        node("n0", labels={"kubernetes.io/hostname": "n0"}),
        node("n1", labels={"kubernetes.io/hostname": "n1"}),
    ]
    anti = {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": "kubernetes.io/hostname",
                }
            ]
        }
    }
    # existing pod repels app=web pods
    existing = pod("lonely", labels={"app": "db"}, affinity=anti, node_name="n0")
    new = pod("web-0", labels={"app": "web"})
    o, results = schedule(nodes, [existing, new])
    assert "existing pods anti-affinity" in results[0].filter["n0"]["InterPodAffinity"]
    assert results[0].selected_node == "n1"


def test_preferred_interpod_affinity_scoring():
    nodes = [
        node("n0", labels={"kubernetes.io/hostname": "n0"}),
        node("n1", labels={"kubernetes.io/hostname": "n1"}),
    ]
    pref = {
        "podAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {
                    "weight": 100,
                    "podAffinityTerm": {
                        "labelSelector": {"matchLabels": {"app": "cache"}},
                        "topologyKey": "kubernetes.io/hostname",
                    },
                }
            ]
        }
    }
    existing = pod("cache-0", labels={"app": "cache"}, node_name="n1")
    new = pod("web-0", affinity=pref)
    o, results = schedule(nodes, [existing, new])
    r = results[0]
    assert r.selected_node == "n1"
    assert int(r.final_score["n1"]["InterPodAffinity"]) == 100


def test_image_locality():
    img = [{"names": ["registry/app:v1"], "sizeBytes": 500 * 1024 * 1024}]
    nodes = [node("n0", images=img), node("n1")]
    o, results = schedule(nodes, [pod("p0", images=["registry/app:v1"])])
    r = results[0]
    assert int(r.score["n0"]["ImageLocality"]) > int(r.score["n1"]["ImageLocality"])


def test_volume_binding_missing_pvc():
    nodes = [node("n0")]
    p = pod("p0", volumes=[{"name": "v", "persistentVolumeClaim": {"claimName": "nope"}}])
    o, results = schedule(nodes, [p])
    assert results[0].status == "Unschedulable"
    assert 'persistentvolumeclaim "nope" not found' in results[0].pre_filter_status["VolumeBinding"]


def test_volume_binding_node_affinity_conflict():
    nodes = [
        node("n0", labels={"topology.kubernetes.io/zone": "a"}),
        node("n1", labels={"topology.kubernetes.io/zone": "b"}),
    ]
    pvc = {
        "metadata": {"name": "claim", "namespace": "default"},
        "spec": {"volumeName": "pv0"},
    }
    pv = {
        "metadata": {"name": "pv0"},
        "spec": {
            "nodeAffinity": {
                "required": {
                    "nodeSelectorTerms": [
                        {
                            "matchExpressions": [
                                {"key": "topology.kubernetes.io/zone", "operator": "In", "values": ["b"]}
                            ]
                        }
                    ]
                }
            }
        },
    }
    p = pod("p0", volumes=[{"name": "v", "persistentVolumeClaim": {"claimName": "claim"}}])
    o, results = schedule(nodes, [p], pvcs=[pvc], pvs=[pv])
    r = results[0]
    assert r.filter["n0"]["VolumeBinding"] == "node(s) had volume node affinity conflict"
    assert r.selected_node == "n1"


def test_preemption():
    pcs = [{"metadata": {"name": "high"}, "value": 1000}]
    nodes = [node("n0", cpu="2", mem="4Gi")]
    low = pod("low", cpu="1500m", priority=0, node_name="n0")
    high = pod("high-pod", cpu="1500m", priority_class="high")
    o, results = schedule(nodes, [low, high], priorityclasses=pcs)
    nominated = [r for r in results if r.status == "Nominated"]
    assert nominated and nominated[0].nominated_node == "n0"
    assert nominated[0].preemption_victims == ["default/low"]
    scheduled = [r for r in results if r.status == "Scheduled" and r.pod_name == "high-pod"]
    assert scheduled and scheduled[0].selected_node == "n0"


def test_priority_queue_order():
    pcs = [{"metadata": {"name": "high"}, "value": 1000}]
    nodes = [node("n0", cpu="1", mem="4Gi")]
    # only room for one 1-cpu pod; high-priority pod should be scheduled first
    a = pod("low-pod", cpu="800m", priority=0)
    b = pod("high-pod", cpu="800m", priority_class="high")
    o, results = schedule(nodes, [a, b], priorityclasses=pcs)
    by_name = {r.pod_name: r for r in results}
    assert by_name["high-pod"].status == "Scheduled"


def test_annotations_shape():
    nodes = [node("n0")]
    o, results = schedule(nodes, [pod("p0")])
    ann = results[0].to_annotations()
    assert ann["scheduler-simulator/selected-node"] == "n0"
    filt = json.loads(ann["scheduler-simulator/filter-result"])
    assert filt["n0"]["NodeResourcesFit"] == "passed"
    final = json.loads(ann["scheduler-simulator/finalscore-result"])
    assert "NodeResourcesBalancedAllocation" in final["n0"]
    assert set(ann.keys()) == {
        f"scheduler-simulator/{k}"
        for k in (
            "prefilter-result-status", "prefilter-result", "filter-result",
            "postfilter-result", "prescore-result", "score-result",
            "finalscore-result", "reserve-result", "permit-result",
            "permit-result-timeout", "prebind-result", "bind-result",
            "selected-node",
        )
    }


def test_custom_config_weights():
    cfg = SchedulerConfiguration.from_dict(
        {
            "profiles": [
                {
                    "schedulerName": "default-scheduler",
                    "plugins": {
                        "score": {
                            "disabled": [{"name": "*"}],
                            "enabled": [{"name": "NodeResourcesFit", "weight": 2}],
                        }
                    },
                }
            ]
        }
    )
    nodes = [node("n0", cpu="4"), node("n1", cpu="8")]
    o, results = schedule(nodes, [pod("p0", cpu="1")], config=cfg)
    r = results[0]
    # only NodeResourcesFit contributes, doubled
    assert set(r.final_score["n0"].keys()) == {"NodeResourcesFit"}
    assert r.selected_node == "n1"
