"""The packed low-precision encoding plane (KSS_DTYPE_POLICY=packed,
engine/packing.py, docs/performance.md "Encoding widths").

Four contract families:

* **Primitives** — host bitpack / device unpack round-trips, the
  narrow-int fit rule, and the per-field overflow guard that keeps a
  narrowed plane honest under delta updates.
* **Parity** — PACKED placements and trace bytes are PIN-IDENTICAL to
  the TPU32 baseline on a label-rich affinity cluster, sequential and
  gang, while the encoded-cluster device bytes shrink; and the compile
  signature keeps the policies (and the logical lane counts behind the
  packed words, which the word shapes alone cannot recover) distinct.
* **EXACT vs TPU32 quantities** — the satellite property: for
  Mi-granular memory and integral millicores, the i64 EXACT plane and
  the i32 TPU32 plane place every pod identically across seeded
  randomized clusters.
* **Policy flip** — a dtype-policy change is a DISTINCT compiled
  program, so the delta encoder must force a full re-encode (reason
  ``dtype-policy-change``) and the serving layer counts it
  (``encodePolicyMisses`` / ``kss_encode_policy_misses_total``).
"""

from __future__ import annotations

import random

import jax.numpy as jnp
import numpy as np
import pytest

from kube_scheduler_simulator_tpu.engine import (
    EXACT,
    PACKED,
    TPU32,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.engine.delta import DeltaEncoder
from kube_scheduler_simulator_tpu.engine.engine import (
    BatchedScheduler,
    supported_config,
)
from kube_scheduler_simulator_tpu.engine.packing import (
    PACK_MIN_DIM,
    encoded_device_bytes,
    narrow_int_np,
    pack_bits_np,
    rows_fit,
    unpack_bits,
    unpack_bits_np,
)
from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.synth import synthetic_affinity_cluster

from helpers import node, pod


# -- primitives --------------------------------------------------------------


@pytest.mark.parametrize("shape", [(3, 8), (5, 33), (2, 4, 40), (7, 64)])
def test_pack_unpack_roundtrip(shape):
    rng = np.random.default_rng(sum(shape))
    m = rng.random(shape) < 0.5
    words = pack_bits_np(m)
    assert words.dtype == np.uint32
    assert words.shape == (*shape[:-1], -(-shape[-1] // 32))
    np.testing.assert_array_equal(unpack_bits_np(words, shape[-1]), m)
    # the DEVICE unpack (the one fused into the kernels) agrees bit for
    # bit with the host mirror the delta encoder keeps
    dev = np.asarray(unpack_bits(jnp.asarray(words), shape[-1]))
    np.testing.assert_array_equal(dev, m)


def test_narrow_int_fit_rule():
    a = np.array([[0, 127], [5, 90]], np.int32)
    # int8 is reserved for enum families; plain id/count planes stop
    # at int16 (delta rows would overflow int8 under vocabulary growth)
    assert narrow_int_np(a).dtype == np.int16
    assert narrow_int_np(a, enum8=True).dtype == np.int8
    b = np.array([[0, 300]], np.int32)  # overflows int8 even as enum
    assert narrow_int_np(b, enum8=True).dtype == np.int16
    c = np.array([[0, 1 << 20]], np.int32)  # stays wide
    assert narrow_int_np(c).dtype == np.int32
    # rows_fit is the delta path's overflow guard for an ALREADY
    # narrowed plane: in-range rows pass, out-of-range rows refuse
    assert rows_fit([np.array([1, 2])], np.dtype(np.int8))
    assert not rows_fit([np.array([300])], np.dtype(np.int8))


def test_packed_encoding_shapes_and_bytes():
    # 96 pods: enough label-pair vocabulary to cross PACK_MIN_DIM lanes
    nodes, pods = synthetic_affinity_cluster(32, 96, seed=5)
    cfg = supported_config()
    wide = encode_cluster(nodes, pods, cfg, policy=TPU32)
    packed = encode_cluster(nodes, pods, cfg, policy=PACKED)
    pd = packed.aux.get("packed_dims") or {}
    assert pd, "a label-rich cluster must bitpack at least one plane"
    for name, n in pd.items():
        leaf = getattr(
            packed.arrays,
            name,
            getattr(packed.arrays.rel, name, None),
        )
        assert leaf is not None
        assert leaf.dtype == jnp.uint32
        assert n >= PACK_MIN_DIM
        assert leaf.shape[-1] == -(-n // 32)
    assert (
        encoded_device_bytes(packed)["total"]
        < encoded_device_bytes(wide)["total"]
    )


# -- parity ------------------------------------------------------------------


def test_packed_sequential_parity_placements_and_trace():
    import jax

    nodes, pods = synthetic_affinity_cluster(24, 72, seed=9)
    cfg = supported_config()
    base = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=TPU32), record=True
    )
    packed = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=PACKED), record=True
    )
    bstate, btrace = base.run()
    pstate, ptrace = packed.run()
    np.testing.assert_array_equal(
        np.asarray(bstate.assignment), np.asarray(pstate.assignment)
    )
    bleaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(btrace)]
    pleaves = [np.asarray(x) for x in jax.tree_util.tree_leaves(ptrace)]
    assert len(bleaves) == len(pleaves)
    for b, p in zip(bleaves, pleaves):
        assert b.dtype == p.dtype  # trace BYTES identical, not just values
        np.testing.assert_array_equal(b, p)


def test_packed_gang_parity_placements():
    from kube_scheduler_simulator_tpu.engine.gang import GangScheduler

    nodes, pods = synthetic_affinity_cluster(16, 48, seed=13)
    cfg = supported_config()
    base = GangScheduler(
        encode_cluster(nodes, pods, cfg, policy=TPU32), chunk=16
    )
    base.run()
    packed = GangScheduler(
        encode_cluster(nodes, pods, cfg, policy=PACKED), chunk=16
    )
    packed.run()
    assert base.placements() == packed.placements()


def test_compile_signature_keys_policy_and_logical_dims():
    nodes, pods = synthetic_affinity_cluster(32, 96, seed=5)
    cfg = supported_config()
    wide = encode_cluster(nodes, pods, cfg, policy=TPU32)
    packed = encode_cluster(nodes, pods, cfg, policy=PACKED)
    sig_wide = BatchedScheduler.compile_signature(wide)
    sig_packed = BatchedScheduler.compile_signature(packed)
    # a policy flip is a distinct compile (and a distinct AOT bundle)
    assert sig_wide != sig_packed
    # the word count ceil(n/32) is not injective in the logical lane
    # count, so the signature must carry the logical dims themselves
    pd = tuple(sorted((packed.aux.get("packed_dims") or {}).items()))
    assert pd in sig_packed


# -- EXACT vs TPU32 Mi-granular quantities (satellite property) --------------


def _mi_cluster(rng: random.Random):
    nodes = [
        node(
            f"n{i}",
            cpu=str(rng.choice([4, 8, 16])),
            mem=f"{rng.choice([8, 16, 32])}Gi",
            labels={"zone": rng.choice(["a", "b"])},
        )
        for i in range(8)
    ]
    pods = []
    for i in range(24):
        kw = {}
        if rng.random() < 0.3:
            kw["node_selector"] = {"zone": rng.choice(["a", "b"])}
        pods.append(
            pod(
                f"p{i}",
                cpu=f"{rng.randrange(50, 3000, 50)}m",
                mem=f"{rng.randrange(1, 128) * 16}Mi",
                labels={"app": f"g{i % 4}"},
                **kw,
            )
        )
    return nodes, pods


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_exact_vs_tpu32_placements_agree_on_mi_quantities(seed):
    """Mi-granular memory and integral millicores fit the i32 plane
    exactly, so the EXACT (i64) and TPU32 (i32) policies must place
    every pod identically — the quantization-safety property the TPU32
    default rests on."""
    rng = random.Random(seed)
    nodes, pods = _mi_cluster(rng)
    cfg = supported_config()
    exact = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT), record=False
    )
    i32 = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=TPU32), record=False
    )
    estate, _ = exact.run()
    istate, _ = i32.run()
    np.testing.assert_array_equal(
        np.asarray(estate.assignment), np.asarray(istate.assignment)
    )


# -- policy flip: full re-encode + the serving counter -----------------------


def test_policy_change_forces_full_reencode():
    store = ResourceStore()
    store.apply("nodes", node("n0", cpu="8"))
    for i in range(4):
        store.apply("pods", pod(f"p{i}"))
    cfg = supported_config()
    delta = DeltaEncoder(policy=TPU32)
    _, info = delta.encode(store, cfg)
    assert info["mode"] == "full"
    store.apply("pods", pod("p-new"))
    _, info = delta.encode(store, cfg)
    assert info["mode"] == "delta"
    # the serving layer flips the policy attribute when KSS_DTYPE_POLICY
    # changes between passes; the retained encoding's dtypes are wrong
    # for the new program, so the next pass must be a full re-encode
    delta.policy = PACKED
    _, info = delta.encode(store, cfg)
    assert info == {"mode": "full", "reason": "dtype-policy-change"}
    # and warm again afterwards
    store.apply("pods", pod("p-newer"))
    _, info = delta.encode(store, cfg)
    assert info["mode"] == "delta"


def test_encode_policy_miss_counter():
    from kube_scheduler_simulator_tpu.utils.metrics import (
        SchedulingMetrics,
        render_prometheus,
    )

    m = SchedulingMetrics()
    m.record_encode_policy_miss()
    snap = m.snapshot()
    assert snap["phases"]["encodePolicyMisses"] == 1
    text = render_prometheus(snap)
    assert "kss_encode_policy_misses_total 1" in text


def test_envcheck_dtype_policy_validator():
    from kube_scheduler_simulator_tpu.utils import envcheck

    assert "KSS_DTYPE_POLICY" in envcheck.KNOWN
    for ok in ("", "exact", "i32", "tpu32", "packed", "PACKED"):
        assert envcheck.check_env({"KSS_DTYPE_POLICY": ok}) == [], ok
    assert envcheck.check_env({"KSS_DTYPE_POLICY": "float8"}) != []
