"""parallel/: mesh build, node-axis sharding registry, weight sweeps on
the 8-device virtual CPU mesh (conftest.py forces the platform)."""

import jax
import numpy as np
import pytest

from kube_scheduler_simulator_tpu.engine import TPU32, BatchedScheduler, encode_cluster
from kube_scheduler_simulator_tpu.engine.engine import supported_config
from kube_scheduler_simulator_tpu.parallel import (
    NODE_AXIS_FIELDS,
    WeightSweep,
    build_mesh,
    shard_encoded,
    surviving_mesh,
    weights_for,
)
from kube_scheduler_simulator_tpu.synth import synthetic_cluster

from helpers import node, pod


def _leaf_fields(obj, out):
    for name in obj.__dataclass_fields__:
        leaf = getattr(obj, name)
        if hasattr(leaf, "__dataclass_fields__"):
            _leaf_fields(leaf, out)
        else:
            out[name] = leaf
    return out


class TestNodeAxisRegistry:
    def test_registry_complete_and_exact(self):
        """Every array whose axis 0 is the node axis must be registered —
        and nothing else. Uses a node count (37) no other dimension hits."""
        N = 37
        nodes, pods = synthetic_cluster(N, 5, seed=1)
        enc = encode_cluster(nodes, pods, supported_config(), policy=TPU32)
        fields = {}
        _leaf_fields(enc.arrays, fields)
        _leaf_fields(enc.state0, fields)
        node_axis = {
            name
            for name, leaf in fields.items()
            if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == N
        }
        assert node_axis == set(NODE_AXIS_FIELDS) & node_axis
        missing = node_axis - NODE_AXIS_FIELDS
        assert not missing, f"unregistered node-axis fields: {missing}"
        phantom = {
            f
            for f in NODE_AXIS_FIELDS
            if f in fields and fields[f].shape[0] != N
        }
        assert not phantom, f"registered non-node-axis fields: {phantom}"


class TestMesh:
    def test_default_factorization(self):
        mesh = build_mesh(8)
        assert mesh.shape == {"replicas": 4, "nodes": 2}

    def test_explicit_factors_validated(self):
        with pytest.raises(ValueError):
            build_mesh(8, replicas=3)
        mesh = build_mesh(8, replicas=2, node_shards=4)
        assert mesh.shape == {"replicas": 2, "nodes": 4}

    def test_odd_device_count_falls_to_single_node_shard(self):
        """The rebuild edge case the execution ladder hits: shrinking 8
        devices to an odd survivor count must factor to node_shards=1
        (the replicas axis absorbs everything)."""
        mesh = build_mesh(7)
        assert mesh.shape == {"replicas": 7, "nodes": 1}
        assert mesh.devices.size == 7

    def test_explicit_surviving_device_subset(self):
        """build_mesh over an explicit device subset — the shrink rung
        hands it the survivors, not a prefix of jax.devices()."""
        subset = jax.devices()[2:6]
        mesh = build_mesh(devices=subset)
        assert mesh.shape == {"replicas": 2, "nodes": 2}
        assert set(mesh.devices.flat) == set(subset)

    def test_bad_factorization_error_names_both_factors(self):
        with pytest.raises(ValueError, match=r"replicas \(3\) x node_shards \(3\)"):
            build_mesh(8, replicas=3, node_shards=3)

    def test_requesting_more_devices_than_present(self):
        with pytest.raises(ValueError, match="devices requested"):
            build_mesh(9)


class TestSurvivingMesh:
    def test_shrink_drops_lost_and_narrows_replicas(self):
        devices = jax.devices()
        mesh = surviving_mesh({devices[0]})
        assert mesh.shape == {"replicas": 7, "nodes": 1}
        assert devices[0] not in set(mesh.devices.flat)

    def test_shrink_even_survivors_keeps_two_node_shards(self):
        devices = jax.devices()
        mesh = surviving_mesh(set(devices[:2]))
        assert mesh.shape == {"replicas": 3, "nodes": 2}

    def test_nothing_surviving_raises(self):
        with pytest.raises(ValueError, match="no devices survive"):
            surviving_mesh(set(jax.devices()))


class TestShardEncoded:
    def test_node_axis_divisibility_enforced(self):
        mesh = build_mesh(8)
        nodes, pods = synthetic_cluster(5, 4, seed=2)
        enc = encode_cluster(nodes, pods, supported_config(), policy=TPU32)
        with pytest.raises(ValueError):
            shard_encoded(enc, mesh)

    def test_sharded_run_matches_unsharded(self):
        mesh = build_mesh(8)
        nodes, pods = synthetic_cluster(16, 24, seed=3)
        enc = encode_cluster(
            nodes, pods, supported_config(), policy=TPU32, node_capacity=16
        )
        sched = BatchedScheduler(enc, record=False)
        want_state, want_sel = jax.jit(sched.run_fn)(
            enc.arrays, enc.state0, np.asarray(enc.queue), sched.weights
        )
        arrays, state0, queue = shard_encoded(enc, mesh)
        got_state, got_sel = jax.jit(sched.run_fn)(
            arrays, state0, queue, sched.weights
        )
        np.testing.assert_array_equal(np.asarray(want_sel), np.asarray(got_sel))
        np.testing.assert_array_equal(
            np.asarray(want_state.assignment), np.asarray(got_state.assignment)
        )


class TestWeightSweep:
    def test_weights_for(self):
        nodes, pods = synthetic_cluster(4, 4, seed=4)
        enc = encode_cluster(nodes, pods, supported_config(), policy=TPU32)
        w = weights_for(enc, {"TaintToleration": 9})
        specs = dict(enc.config.score_plugins())
        assert len(w) == len(specs)
        with pytest.raises(KeyError):
            weights_for(enc, {"NotAPlugin": 1})

    def test_sweep_matches_sequential_runs(self):
        nodes, pods = synthetic_cluster(8, 16, seed=5)
        enc = encode_cluster(nodes, pods, supported_config(), policy=TPU32)
        sweep = WeightSweep(enc)
        base = np.asarray(sweep.sched.weights)
        variants = np.stack([base + i for i in range(4)])
        _, sels = sweep.run(variants)
        assert sels.shape == (4, len(enc.queue))
        for v in range(4):
            sched = BatchedScheduler(enc, record=False)
            _, want = sched.run(weights=variants[v].astype(base.dtype))
            np.testing.assert_array_equal(np.asarray(want), np.asarray(sels)[v])

    def test_sweep_with_preemption_matches_sequential(self):
        """DefaultPreemption enabled under vmap (masked mode): every
        variant's placements must equal a sequential cond-mode run with
        that variant's weights, on a workload where preemption fires."""
        from test_engine_parity_preempt import preempt_config

        nodes = [node(f"n{i}", cpu="2", pods="8") for i in range(4)]
        pds = [
            pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}")
            for i in range(4)
        ] + [pod(f"high-{i}", cpu="1200m", priority=100) for i in range(3)]
        enc = encode_cluster(nodes, pds, preempt_config(), policy=TPU32)
        sweep = WeightSweep(enc)
        base = np.asarray(sweep.sched.weights)
        variants = np.stack([base + 3 * i for i in range(4)])
        states, _ = sweep.run(variants)
        assigns = np.asarray(states.assignment)
        fired = False
        for v in range(4):
            sched = BatchedScheduler(enc, record=True)
            st, trace = sched.run(weights=variants[v].astype(base.dtype))
            np.testing.assert_array_equal(
                np.asarray(st.assignment), assigns[v], err_msg=f"variant {v}"
            )
            fired = fired or bool(np.asarray(trace[5]).any())
        assert fired  # the workload exercised the dry-run path

    def test_masked_mode_matches_phase_mode(self):
        """The two preemption strategies are the same semantics priced
        differently: masked pays the dry-run every step, phase pays it
        per event. Same workload, same weights -> identical states."""
        from test_engine_parity_preempt import preempt_config

        nodes = [node(f"n{i}", cpu="2", pods="8") for i in range(4)]
        pds = [
            pod(f"low-{i}", cpu="1500m", priority=1, node_name=f"n{i}")
            for i in range(4)
        ] + [pod(f"high-{i}", cpu="1200m", priority=100) for i in range(3)]
        enc = encode_cluster(nodes, pds, preempt_config(), policy=TPU32)
        phase = WeightSweep(enc)  # auto -> phase
        assert phase.preempt == "phase"
        masked = WeightSweep(enc, preempt="masked")
        base = np.asarray(phase.sched.weights)
        variants = np.stack([base + 3 * i for i in range(3)])
        st_p, sels_p = phase.run(variants)
        st_m, sels_m = masked.run(variants)
        np.testing.assert_array_equal(
            np.asarray(st_p.assignment), np.asarray(st_m.assignment)
        )
        np.testing.assert_array_equal(np.asarray(sels_p), np.asarray(sels_m))

    def test_record_mode_falls_back_to_masked(self):
        """record=True needs the in-scan trace, which only the masked
        strategy produces — auto must resolve there, not to phase."""
        from test_engine_parity_preempt import preempt_config

        nodes = [node("n0", cpu="2", pods="8")]
        pds = [pod("p0", cpu="1")]
        enc = encode_cluster(nodes, pds, preempt_config(), policy=TPU32)
        assert WeightSweep(enc, record=True).preempt == "masked"

    def test_preempt_off_rejects_preemption_config(self):
        from test_engine_parity_preempt import preempt_config

        nodes = [node("n0", cpu="2", pods="8")]
        pds = [pod("p0", cpu="1")]
        enc = encode_cluster(nodes, pds, preempt_config(), policy=TPU32)
        with pytest.raises(ValueError):
            WeightSweep(enc, preempt="off")

    def test_mesh_sweep_all_scheduled_and_decoded(self):
        mesh = build_mesh(8)
        nodes, pods = synthetic_cluster(16, 24, seed=6)
        enc = encode_cluster(
            nodes, pods, supported_config(), policy=TPU32, node_capacity=16
        )
        sweep = WeightSweep(enc, mesh=mesh)
        base = np.asarray(sweep.sched.weights)
        variants = np.stack([base + i for i in range(8)])  # 8 % 4 reps == 0
        _, sels = sweep.run(variants)
        assert (np.asarray(sels) >= 0).all()
        pl = sweep.placements(sels)
        assert len(pl) == 8 and all(len(d) == len(enc.queue) for d in pl)
        with pytest.raises(ValueError):
            sweep.run(variants[:3])  # 3 % 4 != 0


class TestGangSweep:
    def test_mesh_sharded_gang_sweep_matches_single_variant(self):
        import jax
        import numpy as np

        from kube_scheduler_simulator_tpu.engine import TPU32, encode_cluster
        from kube_scheduler_simulator_tpu.engine.gang import GangScheduler
        from kube_scheduler_simulator_tpu.parallel import GangSweep, build_mesh
        from kube_scheduler_simulator_tpu.parallel.sweep import weights_for
        from kube_scheduler_simulator_tpu.synth import synthetic_cluster
        from test_engine_parity import restricted_config

        mesh = build_mesh(8)  # 4 replicas x 2 node shards (virtual CPU)
        n_shards = mesh.shape["nodes"]
        cfg = restricted_config()
        nodes, pods = synthetic_cluster(8, 24, seed=5)
        enc = encode_cluster(
            nodes, pods, cfg, policy=TPU32, node_capacity=8 * n_shards
        )
        sweep = GangSweep(enc, mesh=mesh, chunk=16)
        variants = [
            {},
            {"NodeResourcesFit": 5},
            {"NodeResourcesBalancedAllocation": 9},
            {"NodeResourcesFit": 2},
        ]
        w = np.stack([weights_for(enc, ov) for ov in variants])
        assignments, rounds = sweep.run(w)
        assert assignments.shape[0] == 4
        assert int(np.asarray(rounds).max()) >= 1
        placements = sweep.placements(assignments)
        # every variant schedules the full queue on this easy cluster
        for d in placements:
            assert all(v for v in d.values())
        # variant 0 must equal an unsharded, unvmapped gang run
        solo = GangScheduler(
            encode_cluster(
                nodes, pods, cfg, policy=TPU32, node_capacity=8 * n_shards
            ),
            chunk=16,
        )
        solo.run()
        assert placements[0] == solo.placements()

    def test_static_loop_gang_sweep_matches_dynamic(self):
        """loop="static" (the scans-only class the experimental TPU
        backend compiles) must place every variant exactly like the
        dynamic sweep, including when a small per-pass budget forces the
        vmapped auto-resume path."""
        import numpy as np

        from kube_scheduler_simulator_tpu.engine import TPU32, encode_cluster
        from kube_scheduler_simulator_tpu.parallel import GangSweep
        from kube_scheduler_simulator_tpu.parallel.sweep import weights_for
        from kube_scheduler_simulator_tpu.synth import synthetic_cluster
        from test_engine_parity import restricted_config

        cfg = restricted_config()
        # contended: 24 pods over 4 nodes needs ~6 committing rounds,
        # well past the default static budget of ceil(24/4)+4 = 10?  no:
        # make the budget tight explicitly via the gang's static_rounds
        nodes, pods = synthetic_cluster(4, 24, seed=5)
        enc = encode_cluster(nodes, pods, cfg, policy=TPU32)
        dyn = GangSweep(enc, chunk=16)
        stat = GangSweep(enc, chunk=16, loop="static")
        # tighten the budget to force at least one auto-resume pass
        stat.gang.static_rounds = 3
        variants = [{}, {"NodeResourcesFit": 5}, {"NodeResourcesBalancedAllocation": 9}]
        w = np.stack([weights_for(enc, ov) for ov in variants])
        a_dyn, _ = dyn.run(w)
        a_stat, _ = stat.run(w)
        np.testing.assert_array_equal(np.asarray(a_dyn), np.asarray(a_stat))
        for d in stat.placements(a_stat):
            assert sum(1 for v in d.values() if v) > 0


def test_windowed_gang_sweep_matches_per_variant_windowed_runs():
    """eval_window under vmap: the row-subset round pipeline is a
    STATIC shrink (unlike compaction's cond), so a windowed GangSweep
    must place every variant exactly like a per-variant windowed
    GangScheduler run — and place everything on an easy cluster."""
    import numpy as np

    from kube_scheduler_simulator_tpu.engine import TPU32, encode_cluster
    from kube_scheduler_simulator_tpu.engine.gang import GangScheduler
    from kube_scheduler_simulator_tpu.parallel import GangSweep
    from kube_scheduler_simulator_tpu.parallel.sweep import weights_for
    from kube_scheduler_simulator_tpu.synth import synthetic_cluster
    from test_engine_parity import restricted_config

    cfg = restricted_config()
    nodes, pods = synthetic_cluster(8, 48, seed=9)
    enc = encode_cluster(nodes, pods, cfg, policy=TPU32)
    for loop in ("dynamic", "static"):
        sweep = GangSweep(enc, chunk=8, loop=loop, eval_window=8)
        variants = [{}, {"NodeResourcesFit": 4}, {"NodeResourcesBalancedAllocation": 7}]
        w = np.stack([weights_for(enc, ov) for ov in variants])
        assignments, _ = sweep.run(w)
        placements = sweep.placements(assignments)
        for i, ov in enumerate(variants):
            assert all(v for v in placements[i].values()), (loop, i)
            solo = GangScheduler(
                encode_cluster(nodes, pods, cfg, policy=TPU32),
                chunk=8, loop=loop, eval_window=8, compact=False,
            )
            solo.run(weights_for(enc, ov))
            assert placements[i] == solo.placements(), (loop, i)
