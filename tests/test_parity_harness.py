"""The REST parity harness (tools/parity_harness.py) driven against two
live instances of this framework's own server — proves the harness
mechanics (reset → import → trigger → poll → extract → diff) end-to-end
so it is ready to point at the Go reference when one is reachable."""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))

from parity_harness import Backend, diff_results, run_backend  # noqa: E402

from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import SimulatorService

from helpers import node, pod


def _snapshot():
    return {
        "nodes": [node(f"n{i}", cpu=str(2 + i % 2)) for i in range(4)],
        "pods": [pod(f"p{i}", cpu=f"{300 + 50 * (i % 4)}m") for i in range(10)],
    }


def test_two_identical_backends_reach_parity():
    srv_a = SimulatorServer(SimulatorService(), port=0).start()
    srv_b = SimulatorServer(SimulatorService(), port=0).start()
    try:
        snap = _snapshot()
        res_a = run_backend(Backend(f"http://127.0.0.1:{srv_a.port}"), snap)
        res_b = run_backend(Backend(f"http://127.0.0.1:{srv_b.port}"), snap)
        assert len(res_a) == 10
        assert all(r["node"] for r in res_a.values())
        # scheduler annotations present (the 13-key record)
        some = next(iter(res_a.values()))
        assert any(k.endswith("filter-result") for k in some["annotations"])
        assert diff_results(res_a, res_b, annotations=True) == []
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_diff_reports_divergence():
    a = {"default/p0": {"node": "n1", "annotations": {}}}
    b = {"default/p0": {"node": "n2", "annotations": {}}}
    lines = diff_results(a, b)
    assert lines and "placement" in lines[0]
    # annotation-level divergence on same placement
    a2 = {"default/p0": {"node": "n1", "annotations": {"scheduler-simulator/score-result": "{}"}}}
    b2 = {"default/p0": {"node": "n1", "annotations": {"scheduler-simulator/score-result": "{...}"}}}
    assert diff_results(a2, b2, annotations=True)
    assert diff_results(a2, b2) == []  # placements agree


def test_cli_roundtrip(tmp_path):
    from parity_harness import main

    srv_a = SimulatorServer(SimulatorService(), port=0).start()
    srv_b = SimulatorServer(SimulatorService(), port=0).start()
    try:
        snap_path = tmp_path / "w.json"
        snap_path.write_text(json.dumps(_snapshot()))
        rc = main([
            "--a", f"http://127.0.0.1:{srv_a.port}",
            "--b", f"http://127.0.0.1:{srv_b.port}",
            "--snapshot", str(snap_path),
            "--annotations",
            "--timeout", "300",
        ])
        assert rc == 0
    finally:
        srv_a.shutdown()
        srv_b.shutdown()
