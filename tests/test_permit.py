"""Permit point: wait/timeout recording via custom permit kernels
(reference wrappedplugin.go:549-575 + resultstore store.go:544-555 —
status AND `timeout.String()` are recorded per permit plugin)."""

import json

from kube_scheduler_simulator_tpu.engine import EXACT, BatchedScheduler, encode_cluster
from kube_scheduler_simulator_tpu.engine import kernels as K
from kube_scheduler_simulator_tpu.sched.results import go_duration

from helpers import node, pod
from test_engine_parity import restricted_config


class TestGoDuration:
    def test_formats(self):
        assert go_duration(0) == "0s"
        assert go_duration(10) == "10s"
        assert go_duration(90) == "1m30s"
        assert go_duration(3723) == "1h2m3s"
        assert go_duration(0.5) == "500ms"
        assert go_duration(0.0005) == "500µs"
        assert go_duration(1.5) == "1.5s"
        assert go_duration(3600) == "1h0m0s"


class TestPermitRecording:
    def _config(self, permit_names):
        cfg = restricted_config(
            filters=("NodeUnschedulable", "NodeName", "NodeResourcesFit"),
        )
        cfg.profile()["plugins"]["permit"] = {
            "disabled": [{"name": "*"}],
            "enabled": [{"name": n} for n in permit_names],
        }
        return cfg

    def test_unregistered_permit_records_success_with_zero_timeout(self):
        nodes = [node("n0")]
        pods = [pod("p0")]
        enc = encode_cluster(nodes, pods, self._config(["SomePermit"]), policy=EXACT)
        sched = BatchedScheduler(enc)
        sched.run()
        res = sched.results()[0]
        assert res.status == "Scheduled"
        assert res.permit == {"SomePermit": "success"}
        assert res.permit_timeout == {"SomePermit": "0s"}
        ann = res.to_annotations()
        assert json.loads(ann["scheduler-simulator/permit-result"]) == {
            "SomePermit": "success"
        }
        assert json.loads(
            ann["scheduler-simulator/permit-result-timeout"]
        ) == {"SomePermit": "0s"}

    def test_custom_permit_kernel_wait_and_timeout(self):
        def build_gate(enc):
            def permit(pod_idx, node_idx):
                ns, name = enc.pod_keys[pod_idx]
                if name.startswith("slow"):
                    return "wait", 12.5
                return "success", 0.0

            return permit

        K.PERMIT_PLUGINS["GatePermit"] = build_gate
        try:
            nodes = [node("n0", cpu="8")]
            pods = [pod("slow-a"), pod("fast-b")]
            enc = encode_cluster(
                nodes, pods, self._config(["GatePermit"]), policy=EXACT
            )
            sched = BatchedScheduler(enc)
            sched.run()
            by_name = {r.pod_name: r for r in sched.results()}
            assert by_name["slow-a"].permit == {"GatePermit": "wait"}
            assert by_name["slow-a"].permit_timeout == {"GatePermit": "12.5s"}
            assert by_name["fast-b"].permit == {"GatePermit": "success"}
            assert by_name["fast-b"].permit_timeout == {"GatePermit": "0s"}
        finally:
            del K.PERMIT_PLUGINS["GatePermit"]

    def test_unschedulable_pod_records_no_permit(self):
        nodes = [node("n0", cpu="100m")]
        pods = [pod("too-big", cpu="4")]
        enc = encode_cluster(nodes, pods, self._config(["SomePermit"]), policy=EXACT)
        sched = BatchedScheduler(enc)
        sched.run()
        res = sched.results()[0]
        assert res.status == "Unschedulable"
        assert res.permit == {}
        assert res.permit_timeout == {}
