"""Out-of-tree plugin demo: NetworkBandwidth registers oracle + kernels +
preemption row by import, then runs under a config that enables it."""

import kube_scheduler_simulator_tpu.plugins.networkbandwidth  # noqa: F401 — registers

from kube_scheduler_simulator_tpu.engine import EXACT, TPU32
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration

from helpers import node, pod
from test_engine_parity import assert_parity, restricted_config


def nb_node(name, limit=None, cpu="4"):
    n = node(name, cpu=cpu)
    if limit is not None:
        n["metadata"]["annotations"] = {
            "node.kubernetes.io/network-limit": limit
        }
    return n


def nb_pod(name, ingress=None, egress=None, cpu="100m", priority=None,
           node_name=None):
    p = pod(name, cpu=cpu, priority=priority, node_name=node_name)
    ann = {}
    if ingress:
        ann["kubernetes.io/ingress-request"] = ingress
    if egress:
        ann["kubernetes.io/egress-request"] = egress
    if ann:
        p["metadata"]["annotations"] = ann
    return p


def nb_config(postfilters=()):
    cfg = restricted_config(
        filters=("NodeUnschedulable", "NodeName", "NodeResourcesFit",
                 "NetworkBandwidth"),
        scores=(("NodeResourcesFit", 1), ("NetworkBandwidth", 2)),
        prefilters=("NodeResourcesFit",),
        prescores=("NodeResourcesFit",),
    )
    if postfilters:
        d = cfg.to_dict()
        d["profiles"][0]["plugins"]["postFilter"]["enabled"] = [
            {"name": n} for n in postfilters
        ]
        return SchedulerConfiguration.from_dict(d)
    return cfg


class TestNetworkBandwidthParity:
    def test_filter_capacity_and_skip(self):
        nodes = [
            nb_node("small", limit="100Mi"),
            nb_node("big", limit="10Gi"),
            nb_node("unlimited"),  # no annotation: plugin skips the node
        ]
        pods = [
            nb_pod("heavy", ingress="1Gi", egress="1Gi"),
            nb_pod("light", ingress="50Mi"),
            nb_pod("none"),  # no request: plugin skips the pod
        ]
        for policy in (EXACT, TPU32):
            got = assert_parity(nodes, pods, nb_config(), policy=policy)
        by = {r.pod_name: r for r in got}
        ann = by["heavy"].to_annotations()
        assert "network bandwidth" in ann["scheduler-simulator/filter-result"]

    def test_allocation_accumulates_across_binds(self):
        nodes = [nb_node("n0", limit="1Gi"), nb_node("n1", limit="1Gi")]
        pods = [
            nb_pod("a", ingress="700Mi", priority=10),
            nb_pod("b", ingress="700Mi", priority=5),
            nb_pod("c", ingress="700Mi", priority=1),
        ]
        got = assert_parity(nodes, pods, nb_config())
        by = {r.pod_name: r for r in got}
        assert by["a"].status == "Scheduled"
        assert by["b"].status == "Scheduled"
        assert by["a"].selected_node != by["b"].selected_node
        assert by["c"].status == "Unschedulable"

    def test_score_prefers_headroom(self):
        nodes = [nb_node("tight", limit="200Mi"), nb_node("roomy", limit="4Gi")]
        pods = [nb_pod("w", ingress="100Mi")]
        got = assert_parity(nodes, pods, nb_config())
        assert got[0].selected_node == "roomy"

    def test_preemption_over_bandwidth(self):
        nodes = [nb_node("only", limit="1Gi")]
        pods = [
            nb_pod("squatter", ingress="900Mi", priority=1, node_name="only"),
            nb_pod("urgent", ingress="900Mi", priority=100),
        ]
        cfg = nb_config(postfilters=("DefaultPreemption",))
        got = assert_parity(nodes, pods, cfg)
        assert any(r.status == "Nominated" for r in got)

    def test_strict_mode_accepts_registered_plugin(self):
        from kube_scheduler_simulator_tpu.engine import (
            BatchedScheduler,
            encode_cluster,
        )

        enc = encode_cluster(
            [nb_node("n0", limit="1Gi")], [nb_pod("p", ingress="1Mi")],
            nb_config(), policy=EXACT,
        )
        BatchedScheduler(enc, strict=True)  # must not raise
