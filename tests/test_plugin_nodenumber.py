"""NodeNumber docs-example plugin: oracle/kernel parity + typed args."""

import kube_scheduler_simulator_tpu.plugins.nodenumber  # noqa: F401 — registers
from kube_scheduler_simulator_tpu.engine import EXACT, BatchedScheduler, encode_cluster
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration
from kube_scheduler_simulator_tpu.sched.oracle import Oracle

from helpers import node, pod


def _config(reverse=False):
    star = [{"name": "*"}]
    plugins = {
        "preFilter": {"disabled": star, "enabled": [{"name": "NodeResourcesFit"}]},
        "filter": {"disabled": star, "enabled": [{"name": "NodeResourcesFit"}]},
        "postFilter": {"disabled": star, "enabled": []},
        "preScore": {"disabled": star, "enabled": []},
        "score": {"disabled": star, "enabled": [{"name": "NodeNumber", "weight": 1}]},
    }
    profile = {"schedulerName": "default-scheduler", "plugins": plugins}
    if reverse:
        profile["pluginConfig"] = [
            {"name": "NodeNumber", "args": {"reverse": True}}
        ]
    return SchedulerConfiguration.from_dict({"profiles": [profile]})


def test_suffix_match_drives_placement():
    nodes = [node("node0"), node("node1"), node("node3")]
    pods = [pod("web1"), pod("db3")]
    cfg = _config()
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    sched = BatchedScheduler(enc, record=True)
    sched.run()
    got = sched.placements()
    assert got[("default", "web1")] == "node1"
    assert got[("default", "db3")] == "node3"


def test_reverse_arg():
    nodes = [node("node1"), node("node2")]
    pods = [pod("app1")]
    enc = encode_cluster(nodes, pods, _config(reverse=True), policy=EXACT)
    sched = BatchedScheduler(enc, record=False)
    sched.run()
    # reverse: the matching node scores 0, the non-matching scores 10
    assert sched.placements()[("default", "app1")] == "node2"


def test_oracle_kernel_parity():
    nodes = [node(f"node{i}") for i in range(5)] + [node("master")]
    pods = [pod(f"p{i}") for i in range(8)] + [pod("nodigit")]
    cfg = _config()
    oracle = Oracle([dict(n) for n in nodes], [dict(p) for p in pods], cfg)
    oracle_res = {
        (r.pod_namespace, r.pod_name): r.selected_node
        for r in oracle.schedule_all()
    }
    enc = encode_cluster(nodes, pods, cfg, policy=EXACT)
    sched = BatchedScheduler(enc, record=True)
    sched.run()
    assert sched.placements() == oracle_res
