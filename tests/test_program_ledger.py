"""The program performance observatory (utils/ledger.py + the
utils/broker.jit hook + the serving routes, docs/observability.md).

The acceptance gates: a CPU chaos run under the armed ledger must
populate ≥1 program entry carrying fingerprint, compile seconds (with
the lowering/backend split), FLOPs/bytes, and call count; `analysis
ledger-diff` must exit non-zero on an injected compile-seconds
regression and zero on identical documents; `/api/v1/metrics` must
report a `coldStart` block with `timeToFirstPassSeconds`; and the
sampled-timing path must be placement-invariant (sampling on/off →
identical placements).
"""

import json
import urllib.request

import jax.numpy as jnp
import pytest

from kube_scheduler_simulator_tpu.analysis.__main__ import main as analysis_main
from kube_scheduler_simulator_tpu.analysis.jaxpr_audit import AuditedJit
from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService
from kube_scheduler_simulator_tpu.server.service import SchedulerService
from kube_scheduler_simulator_tpu.utils import broker as broker_mod
from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod
from kube_scheduler_simulator_tpu.utils import metrics as metrics_mod
from kube_scheduler_simulator_tpu.utils import telemetry

from helpers import node, pod


@pytest.fixture
def ledger(monkeypatch):
    """Arm the ledger for programs jitted inside the test, over a clean
    registry; reset afterwards so records never leak across tests."""
    monkeypatch.setenv(ledger_mod.ENV_VAR, "1")
    ledger_mod.LEDGER.reset()
    yield ledger_mod.LEDGER
    ledger_mod.LEDGER.reset()


def _churn_store(n_nodes=4, n_pods=12) -> ResourceStore:
    store = ResourceStore()
    for i in range(n_nodes):
        store.apply("nodes", node(f"n{i}", cpu="16", mem="32Gi", pods="110"))
    for i in range(n_pods):
        store.apply("pods", pod(f"p{i}", cpu="100m"))
    return store


# -- the broker hook ----------------------------------------------------------


def test_hook_off_by_default(monkeypatch):
    monkeypatch.delenv(ledger_mod.ENV_VAR, raising=False)
    monkeypatch.delenv("KSS_JAXPR_AUDIT", raising=False)
    j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.off"})
    assert not isinstance(j, AuditedJit)


def test_ledger_records_compile_split_cost_and_calls(ledger):
    j = broker_mod.jit(lambda x: x * 2, audit={"label": "t.rec"})
    assert isinstance(j, AuditedJit)
    out = j(jnp.ones((8,), jnp.float32))
    assert float(out[0]) == 2.0  # the AOT dispatch answers correctly
    j(jnp.zeros((8,), jnp.float32))
    j(jnp.ones((16,), jnp.float32))  # new bucket: second program
    snap = ledger.snapshot()
    assert len(snap["programs"]) == 2
    by_calls = sorted(snap["programs"], key=lambda p: -p["calls"])
    first = by_calls[0]
    assert first["label"] == "t.rec"
    assert first["fingerprint"]
    assert first["calls"] == 2
    assert first["compileSeconds"]["total"] > 0
    assert first["compileSeconds"]["lowering"] > 0
    assert first["compileSeconds"]["backend"] > 0
    # the CPU backend exposes the cost + memory models
    assert first["flops"] is not None and first["bytes"] is not None
    assert first["memory"]["argumentBytes"] > 0
    assert first["dispatchSeconds"] > 0
    assert ledger.totals()["calls"] == 3


def test_warm_sampling_every_nth_call(ledger, monkeypatch):
    monkeypatch.setenv(ledger_mod.SAMPLE_VAR, "1")
    j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.warm"})
    for _ in range(4):
        j(jnp.ones((8,), jnp.float32))
    (p,) = ledger.snapshot()["programs"]
    # the first (compile-bearing) call is never sampled
    assert p["warm"]["samples"] == 3
    assert p["warm"]["meanSeconds"] is not None


def test_sampling_off_never_blocks(ledger, monkeypatch):
    monkeypatch.delenv(ledger_mod.SAMPLE_VAR, raising=False)
    j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.nowarm"})
    for _ in range(3):
        j(jnp.ones((8,), jnp.float32))
    (p,) = ledger.snapshot()["programs"]
    assert p["warm"]["samples"] == 0
    assert p["mfu"] is None  # no warm wall, no MFU claim


def test_session_attribution_and_drop(ledger):
    j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.sess"})
    with telemetry.session_context("s-a"):
        j(jnp.ones((8,), jnp.float32))
        j(jnp.ones((8,), jnp.float32))
    j(jnp.ones((8,), jnp.float32))  # sessionless -> "default"
    (p,) = ledger.snapshot()["programs"]
    assert p["sessions"] == {"s-a": 2, "default": 1}
    # the nested-route filter: only programs the session dispatched
    assert ledger.snapshot(session="s-a")["programs"]
    assert ledger.snapshot(session="s-zzz")["programs"] == []
    ledger.drop_session("s-a")
    (p,) = ledger.snapshot()["programs"]
    assert p["sessions"] == {"default": 1}


def test_rebuild_accumulates_compile_wall(ledger):
    # two engines jitting the SAME program (label + fingerprint) merge
    # into one row whose builds/compile wall accumulate — recompile
    # cost must never be hidden by deduplication
    for _ in range(2):
        j = broker_mod.jit(lambda x: x * 3, audit={"label": "t.rebuild"})
        j(jnp.ones((8,), jnp.float32))
    (p,) = ledger.snapshot()["programs"]
    assert p["builds"] == 2
    assert p["calls"] == 2


# -- placement parity ---------------------------------------------------------


def _placements(sample: "str | None", monkeypatch) -> dict:
    monkeypatch.delenv(ledger_mod.ENV_VAR, raising=False)
    monkeypatch.delenv(ledger_mod.SAMPLE_VAR, raising=False)
    if sample is not None:
        monkeypatch.setenv(ledger_mod.ENV_VAR, "1")
        if sample:
            monkeypatch.setenv(ledger_mod.SAMPLE_VAR, sample)
    svc = SchedulerService(_churn_store())
    placements, _, _ = svc.schedule_gang(record=False)
    # drive a second pass so the sampled (post-compile) path runs too
    svc.store.apply("pods", pod("late-1", cpu="100m"))
    second, _, _ = svc.schedule_gang(record=False)
    return {**placements, **second}


def test_sampled_timing_path_is_placement_invariant(monkeypatch):
    # the two extremes cover both switches: ledger fully off vs ledger
    # on with every call sampled (block_until_ready on the hot path)
    ledger_mod.LEDGER.reset()
    try:
        off = _placements(None, monkeypatch)  # ledger off entirely
        sampled = _placements("1", monkeypatch)  # ledger on, sample every call
    finally:
        ledger_mod.LEDGER.reset()
    assert off == sampled
    assert any(v for v in off.values())  # the pass actually scheduled


# -- persistence + diff -------------------------------------------------------


def test_persist_round_trip_and_self_diff_clean(ledger, tmp_path):
    j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.persist"})
    j(jnp.ones((8,), jnp.float32))
    path = str(tmp_path / "ledger" / "kss-program-ledger.json")
    assert ledger.persist(path) == []  # no baseline yet: no drift
    doc = ledger_mod.load_ledger(path)
    assert doc is not None and doc["format"] == ledger_mod.LEDGER_FORMAT
    assert doc["programs"][0]["label"] == "t.persist"
    # identical state re-persisted: drift-free
    assert ledger.persist(path) == []
    assert ledger_mod.diff_ledger(doc, doc) == []


def test_load_rejects_foreign_documents(tmp_path):
    p = tmp_path / "kss-program-ledger.json"
    p.write_text('{"format": "something-else", "programs": []}')
    assert ledger_mod.load_ledger(str(p)) is None
    p.write_text("not json")
    assert ledger_mod.load_ledger(str(p)) is None
    assert ledger_mod.load_ledger(str(tmp_path / "absent.json")) is None


def _doc(programs):
    return {"format": ledger_mod.LEDGER_FORMAT, "programs": programs}


def _prog(label, fp, compile_s=0.5, flops=100.0):
    return {
        "label": label,
        "fingerprint": fp,
        "compileSeconds": {"total": compile_s},
        "flops": flops,
    }


def test_diff_flags_compile_regression_not_improvement():
    base = _doc([_prog("seq.run", "aa", compile_s=1.0)])
    slower = _doc([_prog("seq.run", "aa", compile_s=4.0)])
    faster = _doc([_prog("seq.run", "aa", compile_s=0.2)])
    assert [f.rule for f in ledger_mod.diff_ledger(base, slower)] == ["KSS731"]
    assert ledger_mod.diff_ledger(base, faster) == []
    # jitter below the absolute floor never flags, whatever the ratio
    tiny = _doc([_prog("seq.run", "aa", compile_s=0.01)])
    tiny_slower = _doc([_prog("seq.run", "aa", compile_s=0.5)])
    assert ledger_mod.diff_ledger(tiny, tiny_slower) == []


def test_diff_catches_regression_hidden_behind_a_changed_fingerprint():
    # the blind-spot case: the label survives but its fingerprint
    # changed (an avals/static-arg drift — the recompile class the
    # gate exists for), so no (label, fingerprint) key is shared.
    # The churn itself must flag (KSS735) AND the label-aggregate
    # compile comparison must still see the 25x regression (KSS731).
    base = _doc([_prog("seq.run", "f1", compile_s=2.0)])
    cur = _doc([_prog("seq.run", "f2", compile_s=50.0)])
    rules = sorted(f.rule for f in ledger_mod.diff_ledger(base, cur))
    assert rules == ["KSS731", "KSS735"]


def test_diff_flags_flops_drift_and_program_churn():
    base = _doc([_prog("seq.run", "aa"), _prog("gang.run", "bb")])
    drifted = _doc(
        [_prog("seq.run", "aa", flops=999.0), _prog("new.site", "cc")]
    )
    rules = sorted(f.rule for f in ledger_mod.diff_ledger(base, drifted))
    assert rules == ["KSS732", "KSS733", "KSS734"]


def test_ledger_diff_cli_gate(tmp_path, capsys):
    base = _doc([_prog("seq.run", "aa", compile_s=1.0)])
    bad = _doc([_prog("seq.run", "aa", compile_s=30.0)])
    base_p, bad_p = tmp_path / "base.json", tmp_path / "bad.json"
    base_p.write_text(json.dumps(base))
    bad_p.write_text(json.dumps(bad))
    assert analysis_main(["ledger-diff", str(base_p), str(base_p)]) == 0
    assert analysis_main(["ledger-diff", str(base_p), str(bad_p)]) == 1
    out = capsys.readouterr().out
    assert "KSS731" in out
    # unreadable baseline is a usage error, not "clean"
    assert analysis_main(
        ["ledger-diff", str(tmp_path / "nope.json"), str(base_p)]
    ) == 2


# -- cold-start phase accounting ----------------------------------------------


def test_cold_start_marks_order_and_latch():
    ledger_mod.COLD_START.reset()
    try:
        svc = SchedulerService(_churn_store())
        placements, _, _ = svc.schedule_gang(record=False)
        assert any(v for v in placements.values())
        snap = ledger_mod.COLD_START.snapshot()
        assert snap["complete"] is True
        assert snap["timeToFirstPassSeconds"] > 0
        phases = snap["phases"]
        # encode precedes the engine compile precedes the first pass
        assert phases["firstEncode"] <= phases["firstCompile"]
        assert phases["firstCompile"] <= phases["firstPass"]
        first = snap["timeToFirstPassSeconds"]
        # a second pass never moves the latched marks
        svc.store.apply("pods", pod("late", cpu="100m"))
        svc.schedule_gang(record=False)
        assert (
            ledger_mod.COLD_START.snapshot()["timeToFirstPassSeconds"]
            == first
        )
    finally:
        ledger_mod.COLD_START.reset()


def test_empty_pass_does_not_complete_cold_start():
    ledger_mod.COLD_START.reset()
    try:
        store = ResourceStore()
        store.apply("nodes", node("n0", cpu="16", mem="32Gi", pods="110"))
        svc = SchedulerService(store)  # no pods: nothing schedulable
        svc.schedule_gang(record=False)
        snap = ledger_mod.COLD_START.snapshot()
        assert snap["complete"] is False
        assert snap["timeToFirstPassSeconds"] is None
    finally:
        ledger_mod.COLD_START.reset()


# -- the HTTP surface ---------------------------------------------------------


def _req(port, method, path, body=None, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read()
        return resp.status, json.loads(raw) if raw else None


def _raw(port, path, timeout=300):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as resp:
        return resp.status, resp.read().decode()


@pytest.fixture
def armed_server(ledger):
    ledger_mod.COLD_START.reset()
    srv = SimulatorServer(SimulatorService(), port=0).start()
    yield srv
    srv.shutdown()
    ledger_mod.COLD_START.reset()


def _chaos_body():
    return {
        "name": "obs",
        "seed": 7,
        "horizon": 10.0,
        "schedulerMode": "gang",
        "snapshot": {
            "nodes": [
                node(f"n{i}", cpu="16", mem="32Gi", pods="110")
                for i in range(3)
            ]
        },
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 1.0,
                "count": 5,
                "template": {
                    "metadata": {"name": "churn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
    }


def test_debug_programs_populated_by_chaos_run(armed_server):
    # the acceptance criterion: a CPU-only chaos run populates the
    # ledger, and GET /api/v1/debug/programs answers ≥1 program entry
    # carrying fingerprint, compile seconds, FLOPs/bytes, call count
    code, result = _req(
        armed_server.port, "POST", "/api/v1/lifecycle", _chaos_body()
    )
    assert code == 200 and result["phase"] == "Succeeded"
    code, doc = _req(armed_server.port, "GET", "/api/v1/debug/programs")
    assert code == 200
    assert doc["format"] == ledger_mod.LEDGER_FORMAT
    assert doc["enabled"] is True
    assert len(doc["programs"]) >= 1
    p = doc["programs"][0]
    assert p["fingerprint"]
    assert p["compileSeconds"]["total"] > 0
    assert p["flops"] is not None and p["bytes"] is not None
    assert p["calls"] >= 1

    # the metrics document carries the observatory blocks (schema v3)
    code, m = _req(armed_server.port, "GET", "/api/v1/metrics")
    assert code == 200
    assert m["schemaVersion"] == metrics_mod.METRICS_SCHEMA_VERSION
    assert m["programs"]["enabled"] is True
    assert m["programs"]["count"] >= 1
    cold = m["coldStart"]
    assert cold["complete"] is True
    assert cold["timeToFirstPassSeconds"] > 0
    assert "firstEncode" in cold["phases"]

    # and the Prometheus exposition gains the program families,
    # surviving the strict text-format parse
    code, text = _raw(
        armed_server.port, "/api/v1/metrics?format=prometheus"
    )
    assert code == 200
    families = metrics_mod.parse_prometheus_text(text)
    assert "kss_program_compile_seconds" in families
    assert "kss_program_calls_total" in families
    sample = families["kss_program_calls_total"]["samples"][0]
    assert sample[1]["program"] and sample[1]["fingerprint"]

    # per-session attribution over the same server: a tenant's passes
    # dispatch programs under its session label, the nested route
    # filters to them, and DELETE drops the attribution
    code, sess = _req(
        armed_server.port, "POST", "/api/v1/sessions", {"name": "tenant-a"}
    )
    assert code == 201
    sid = sess["id"]
    base = f"/api/v1/sessions/{sid}"
    _req(armed_server.port, "PUT", f"{base}/resources/nodes", node("n0"))
    _req(
        armed_server.port,
        "PUT",
        f"{base}/resources/pods",
        pod("p0", cpu="100m"),
    )
    code, _ = _req(
        armed_server.port, "POST", f"{base}/schedule?mode=gang&record=0"
    )
    assert code == 200
    code, doc = _req(armed_server.port, "GET", f"{base}/debug/programs")
    assert code == 200 and len(doc["programs"]) >= 1
    assert all(sid in p["sessions"] for p in doc["programs"])
    code, _ = _req(armed_server.port, "DELETE", f"/api/v1/sessions/{sid}")
    assert code == 200
    code, doc = _req(armed_server.port, "GET", "/api/v1/debug/programs")
    assert code == 200
    assert all(sid not in p["sessions"] for p in doc["programs"])


def test_cold_start_block_on_fresh_unarmed_server():
    # the coldStart block is part of the metrics document even with
    # the ledger OFF — phase accounting is always-on (cheap latches)
    ledger_mod.COLD_START.reset()
    srv = SimulatorServer(SimulatorService(), port=0).start()
    try:
        code, m = _req(srv.port, "GET", "/api/v1/metrics")
        assert code == 200
        assert m["coldStart"]["complete"] is False
        assert m["programs"]["enabled"] is False
        _req(srv.port, "PUT", "/api/v1/resources/nodes", node("n0"))
        _req(
            srv.port, "PUT", "/api/v1/resources/pods", pod("p0", cpu="100m")
        )
        code, _ = _req(srv.port, "POST", "/api/v1/schedule?mode=gang&record=0")
        assert code == 200
        code, m = _req(srv.port, "GET", "/api/v1/metrics")
        assert m["coldStart"]["complete"] is True
        assert m["coldStart"]["timeToFirstPassSeconds"] > 0
    finally:
        srv.shutdown()
        ledger_mod.COLD_START.reset()


# -- telemetry counter tracks -------------------------------------------------


def test_lifecycle_emits_pending_pods_counter_track(monkeypatch):
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec

    rec = telemetry.SpanRecorder(capacity=4096)
    telemetry.activate(rec)
    try:
        spec = ChaosSpec.from_dict(
            {
                "name": "counter",
                "seed": 3,
                "horizon": 6.0,
                "schedulerMode": "gang",
                "snapshot": {
                    "nodes": [node("n0", cpu="16", mem="32Gi", pods="110")]
                },
                "arrivals": [
                    {
                        "kind": "poisson",
                        "rate": 1.0,
                        "count": 3,
                        "template": {
                            "metadata": {"name": "churn"},
                            "spec": {
                                "containers": [
                                    {
                                        "name": "c",
                                        "resources": {
                                            "requests": {
                                                "cpu": "100m",
                                                "memory": "64Mi",
                                            }
                                        },
                                    }
                                ]
                            },
                        },
                    }
                ],
            }
        )
        result = LifecycleEngine(spec).run()
        assert result["phase"] == "Succeeded"
        events = rec.snapshot()
    finally:
        telemetry.deactivate()
    pending = [
        e
        for e in events
        if e.get("ph") == "C" and e["name"] == "pending_pods"
    ]
    assert pending, "no pending_pods counter samples in the trace"
    assert all(e["args"]["value"] >= 0 for e in pending)
    telemetry.check_nesting(events)


def test_counter_events_ride_the_flight_recorder(ledger):
    rec = telemetry.SpanRecorder(capacity=256)
    telemetry.activate(rec)
    try:
        j = broker_mod.jit(lambda x: x + 1, audit={"label": "t.counter"})
        j(jnp.ones((8,), jnp.float32))
        j(jnp.ones((8,), jnp.float32))
        events = rec.snapshot()
    finally:
        telemetry.deactivate()
    counters = [e for e in events if e.get("ph") == "C"]
    assert any(e["name"] == "ledger.dispatchSeconds" for e in counters)
    values = [
        e["args"]["value"]
        for e in counters
        if e["name"] == "ledger.dispatchSeconds"
    ]
    assert values == sorted(values)  # cumulative, monotone
    # counter events never disturb span well-formedness
    telemetry.check_nesting(events)
