from fractions import Fraction

import pytest

from kube_scheduler_simulator_tpu.utils.quantity import parse_quantity, format_quantity


@pytest.mark.parametrize(
    "s,expected",
    [
        ("100m", Fraction(1, 10)),
        ("1", Fraction(1)),
        ("1.5", Fraction(3, 2)),
        ("1Gi", Fraction(1024**3)),
        ("512Mi", Fraction(512 * 1024**2)),
        ("1Ki", Fraction(1024)),
        ("2e3", Fraction(2000)),
        ("1E2", Fraction(100)),
        ("5k", Fraction(5000)),
        ("3M", Fraction(3_000_000)),
        ("250n", Fraction(250, 10**9)),
        ("-2", Fraction(-2)),
        ("+2", Fraction(2)),
        (".5", Fraction(1, 2)),
        ("0", Fraction(0)),
    ],
)
def test_parse(s, expected):
    assert parse_quantity(s).value == expected


def test_milli_rounds_up():
    assert parse_quantity("1n").milli == 1
    assert parse_quantity("100m").milli == 100
    assert parse_quantity("1").milli == 1000


def test_units_round_up():
    assert parse_quantity("100m").units == 1
    assert parse_quantity("1Gi").units == 1024**3


@pytest.mark.parametrize("bad", ["", "abc", "1Q", "--1", "1.2.3", "1 Gi"])
def test_invalid(bad):
    with pytest.raises(ValueError):
        parse_quantity(bad)


def test_format_roundtrip():
    assert format_quantity(1024**3) == "1Gi"
    assert format_quantity(2000) == "2k"
    assert format_quantity(0) == "0"
    assert format_quantity(1500) == "1500"
