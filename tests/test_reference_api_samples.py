"""HTTP-level round-trip of the REFERENCE's own API samples.

The reference documents real request/response captures in
simulator/docs/api-samples/v1/{import,export}.md. These tests feed the
exact import bodies from those captures to this framework's server and
assert the reference-documented outcomes: 200 responses, the PV/PVC pair
landing in the store with the claimRef re-linked to the new PVC UID
(export.go:484-514 semantics), and the imported scheduler configuration
surviving a subsequent export. Skipped when the reference checkout is
not present (e.g. public CI).
"""

import json
import re
import urllib.request
from pathlib import Path

import pytest

SAMPLES = Path("/root/reference/simulator/docs/api-samples/v1")

pytestmark = pytest.mark.skipif(
    not SAMPLES.exists(), reason="reference checkout not available"
)


def _extract_json_bodies(md_path: Path) -> list[dict]:
    """Every JSON object that appears as a request/response body line in
    the sample markdown."""
    bodies = []
    for line in md_path.read_text().splitlines():
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                bodies.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return bodies


def _server():
    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
    from kube_scheduler_simulator_tpu.server.service import SimulatorService

    return SimulatorServer(SimulatorService(), port=0).start()


def _req(base, method, path, payload=None):
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        f"{base}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        body = resp.read()
        return resp.status, (json.loads(body) if body else None)


def test_reference_import_sample_round_trips():
    from kube_scheduler_simulator_tpu.server.service import SimulatorService
    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer

    bodies = _extract_json_bodies(SAMPLES / "import.md")
    imports = [b for b in bodies if "pvs" in b and "schedulerConfig" in b]
    assert imports, "no import sample bodies found in the reference doc"
    svc = SimulatorService()
    srv = SimulatorServer(svc, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        for snapshot in imports:
            status, out = _req(base, "POST", "/api/v1/import", snapshot)
            assert status == 200
            assert out.get("errors") in (None, [],), out
            # the sample carries pv1 bound to pvc1: in the store, claimRef
            # must point at the PVC's uid (reference re-link semantics,
            # export.go:484-514)
            pv = svc.store.get("pvs", "pv1")
            pvc = svc.store.get("pvcs", "pvc1", "default")
            if pv and pvc:
                claim = pv["spec"]["claimRef"]
                assert claim["name"] == "pvc1"
                assert claim["uid"] == pvc["metadata"]["uid"]
            # export round-trips the pair (metadata is intentionally
            # cleaned of server-managed fields — snapshot.py _STRIP_META —
            # so linkage is by name on the wire)
            status, exported = _req(base, "GET", "/api/v1/export")
            assert status == 200
            names = {p["metadata"]["name"] for p in exported["pvs"]}
            assert "pv1" in names
            assert {p["metadata"]["name"] for p in exported["pvcs"]} >= {"pvc1"}
            # the imported scheduler config's profile survives
            status, cfg = _req(base, "GET", "/api/v1/schedulerconfiguration")
            assert status == 200
            assert cfg["profiles"][0]["schedulerName"] == "default-scheduler"
            _req(base, "PUT", "/api/v1/reset")
    finally:
        srv.shutdown()


def test_reference_export_sample_shape_matches_ours():
    bodies = _extract_json_bodies(SAMPLES / "export.md")
    refs = [b for b in bodies if "pods" in b and "nodes" in b]
    assert refs, "no export sample bodies found in the reference doc"
    srv = _server()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        _, ours = _req(base, "GET", "/api/v1/export")
        for ref in refs:
            # wire-shape parity: our export carries every top-level key
            # the reference's documented export carries
            missing = set(ref) - set(ours)
            assert not missing, f"export missing reference keys: {missing}"
    finally:
        srv.shutdown()
