"""Replicate-existing-cluster: snapshot ingestion from a live simulator's
export endpoint, IgnoreErr + IgnoreSchedulerConfiguration semantics."""

import json

import pytest

from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService
from kube_scheduler_simulator_tpu.server.replicate import (
    replicate_existing_cluster,
)
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration

from helpers import node, pod


def custom_config():
    return SchedulerConfiguration.from_dict(
        {
            "profiles": [
                {
                    "schedulerName": "default-scheduler",
                    "plugins": {
                        "score": {
                            "disabled": [{"name": "*"}],
                            "enabled": [{"name": "ImageLocality", "weight": 7}],
                        }
                    },
                }
            ]
        }
    )


class TestReplicate:
    def test_from_live_simulator_ignores_config(self):
        src = SimulatorService(custom_config())
        src.store.apply("nodes", node("n0"))
        src.store.apply("pods", pod("w"))
        srv = SimulatorServer(src, port=0).start()
        try:
            dst = SimulatorService()
            errors = replicate_existing_cluster(
                dst, source_url=f"http://127.0.0.1:{srv.port}"
            )
            assert errors == []
            assert [n["metadata"]["name"] for n in dst.store.list("nodes")] == ["n0"]
            assert [p["metadata"]["name"] for p in dst.store.list("pods")] == ["w"]
            # source's custom scheduler config NOT adopted
            enabled = dst.scheduler.get_config()["profiles"][0]["plugins"][
                "score"
            ]["enabled"]
            assert enabled != [{"name": "ImageLocality", "weight": 7}]
        finally:
            srv.shutdown()

    def test_ignore_err_skips_bad_objects(self):
        dst = SimulatorService()
        snap = {
            "nodes": [node("good"), {"metadata": {}}],  # second has no name
            "pods": [],
        }
        errors = replicate_existing_cluster(dst, snapshot=snap)
        assert len(errors) == 1 and "nodes" in errors[0]
        assert [n["metadata"]["name"] for n in dst.store.list("nodes")] == ["good"]

    def test_snapshot_path(self, tmp_path):
        p = tmp_path / "snap.json"
        p.write_text(json.dumps({"nodes": [node("disk-node")]}))
        dst = SimulatorService()
        assert replicate_existing_cluster(dst, snapshot_path=str(p)) == []
        assert dst.store.get("nodes", "disk-node") is not None

    def test_exactly_one_source(self):
        with pytest.raises(ValueError):
            replicate_existing_cluster(SimulatorService())
        with pytest.raises(ValueError):
            replicate_existing_cluster(
                SimulatorService(), snapshot={}, snapshot_path="x"
            )
