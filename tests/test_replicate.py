"""Replicate-existing-cluster: snapshot ingestion from a live simulator's
export endpoint, IgnoreErr + IgnoreSchedulerConfiguration semantics."""

import json

import pytest

from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService
from kube_scheduler_simulator_tpu.server.replicate import (
    replicate_existing_cluster,
)
from kube_scheduler_simulator_tpu.sched.config import SchedulerConfiguration

from helpers import node, pod


def custom_config():
    return SchedulerConfiguration.from_dict(
        {
            "profiles": [
                {
                    "schedulerName": "default-scheduler",
                    "plugins": {
                        "score": {
                            "disabled": [{"name": "*"}],
                            "enabled": [{"name": "ImageLocality", "weight": 7}],
                        }
                    },
                }
            ]
        }
    )


class TestReplicate:
    def test_from_live_simulator_ignores_config(self):
        src = SimulatorService(custom_config())
        src.store.apply("nodes", node("n0"))
        src.store.apply("pods", pod("w"))
        srv = SimulatorServer(src, port=0).start()
        try:
            dst = SimulatorService()
            errors = replicate_existing_cluster(
                dst, source_url=f"http://127.0.0.1:{srv.port}"
            )
            assert errors == []
            assert [n["metadata"]["name"] for n in dst.store.list("nodes")] == ["n0"]
            assert [p["metadata"]["name"] for p in dst.store.list("pods")] == ["w"]
            # source's custom scheduler config NOT adopted
            enabled = dst.scheduler.get_config()["profiles"][0]["plugins"][
                "score"
            ]["enabled"]
            assert enabled != [{"name": "ImageLocality", "weight": 7}]
        finally:
            srv.shutdown()

    def test_ignore_err_skips_bad_objects(self):
        dst = SimulatorService()
        snap = {
            "nodes": [node("good"), {"metadata": {}}],  # second has no name
            "pods": [],
        }
        errors = replicate_existing_cluster(dst, snapshot=snap)
        assert len(errors) == 1 and "nodes" in errors[0]
        assert [n["metadata"]["name"] for n in dst.store.list("nodes")] == ["good"]

    def test_snapshot_path(self, tmp_path):
        p = tmp_path / "snap.json"
        p.write_text(json.dumps({"nodes": [node("disk-node")]}))
        dst = SimulatorService()
        assert replicate_existing_cluster(dst, snapshot_path=str(p)) == []
        assert dst.store.get("nodes", "disk-node") is not None

    def test_exactly_one_source(self):
        with pytest.raises(ValueError):
            replicate_existing_cluster(SimulatorService())
        with pytest.raises(ValueError):
            replicate_existing_cluster(
                SimulatorService(), snapshot={}, snapshot_path="x"
            )
        with pytest.raises(ValueError):
            replicate_existing_cluster(
                SimulatorService(), snapshot={}, kube_apiserver="http://x"
            )


class _FakeApiserver:
    """Canned kube-apiserver: serves the typed List endpoints with the
    real wire shapes (PodList/NodeList/...; kind/apiVersion on the List,
    not on items), optionally requiring a bearer token."""

    def __init__(self, token=""):
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        fixtures = {
            "/api/v1/pods": (
                "PodList",
                [
                    pod("bound", node_name="real-n0"),
                    pod("pending"),
                    {  # system pod in kube-system stays importable
                        "metadata": {"name": "kube-proxy-x", "namespace": "kube-system"},
                        "spec": {"containers": [{"name": "c"}], "nodeName": "real-n0"},
                    },
                ],
            ),
            "/api/v1/nodes": ("NodeList", [node("real-n0"), node("real-n1")]),
            "/api/v1/persistentvolumes": ("PersistentVolumeList", []),
            "/api/v1/persistentvolumeclaims": ("PersistentVolumeClaimList", []),
            "/apis/storage.k8s.io/v1/storageclasses": ("StorageClassList", []),
            "/apis/scheduling.k8s.io/v1/priorityclasses": (
                "PriorityClassList",
                [
                    {
                        "metadata": {"name": "workload-high"},
                        "value": 10000,
                    }
                ],
            ),
            "/api/v1/namespaces": (
                "NamespaceList",
                [{"metadata": {"name": "prod"}}],
            ),
        }
        outer = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):  # noqa: N802
                if token and self.headers.get("Authorization") != f"Bearer {token}":
                    self.send_response(401)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                fx = fixtures.get(self.path)
                if fx is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                kind, items = fx
                body = json.dumps(
                    {"kind": kind, "apiVersion": "v1", "items": items}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestReplicateFromRealCluster:
    """kube-apiserver REST listing → snapshot shape → IgnoreErr import
    (reference replicateexistingcluster.go:40-53 without client-go)."""

    def test_list_cluster_shape(self):
        from kube_scheduler_simulator_tpu.server.replicate import list_cluster

        api = _FakeApiserver()
        try:
            snap = list_cluster(api.url)
        finally:
            api.shutdown()
        assert {
            "pods", "nodes", "pvs", "pvcs",
            "storageClasses", "priorityClasses", "namespaces",
        } <= set(snap)
        assert len(snap["pods"]) == 3
        assert len(snap["nodes"]) == 2
        assert snap["priorityClasses"][0]["value"] == 10000

    def test_replicate_imports_cluster(self):
        api = _FakeApiserver()
        dst = SimulatorService(custom_config())
        try:
            errors = replicate_existing_cluster(dst, kube_apiserver=api.url)
        finally:
            api.shutdown()
        assert errors == []
        assert {n["metadata"]["name"] for n in dst.store.list("nodes")} == {
            "real-n0",
            "real-n1",
        }
        got = dst.store.get("pods", "bound")
        assert got["spec"]["nodeName"] == "real-n0"
        assert dst.store.get("pods", "pending")["spec"].get("nodeName") is None
        assert dst.store.get("namespaces", "prod") is not None
        # config untouched (IgnoreSchedulerConfiguration — the apiserver
        # has none to offer anyway)
        enabled = dst.scheduler.get_config()["profiles"][0]["plugins"][
            "score"
        ]["enabled"]
        assert enabled == [{"name": "ImageLocality", "weight": 7}]

    def test_bearer_token_required_and_sent(self):
        from kube_scheduler_simulator_tpu.server.replicate import list_cluster

        api = _FakeApiserver(token="sekret")
        try:
            with pytest.raises(RuntimeError, match="HTTP 401"):
                list_cluster(api.url)
            snap = list_cluster(api.url, bearer_token="sekret")
        finally:
            api.shutdown()
        assert len(snap["nodes"]) == 2
