"""Run supervision (the robustness PR): the compile watchdog +
degradation ladder in `CompileBroker.get_resilient`, the hardened
speculative worker, the serving layer's eager fallback, and the HTTP
surface's structured-error / 503 mapping (docs/resilience.md)."""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import (
    EngineDegraded,
    SchedulerService,
    SimulatorService,
)
from kube_scheduler_simulator_tpu.utils.broker import (
    CompileBroker,
    CompileDeadlineExceeded,
    CompileUnavailable,
    _call_with_deadline,
    eager_active,
    eager_execution,
    jit as broker_jit,
)
from kube_scheduler_simulator_tpu.utils.metrics import SchedulingMetrics

from helpers import node, pod


class TestWatchdog:
    def test_no_deadline_runs_inline(self):
        tid = threading.get_ident()
        assert _call_with_deadline(threading.get_ident, 0.0) == tid

    def test_deadline_met(self):
        assert _call_with_deadline(lambda: "engine", 5.0) == "engine"

    def test_deadline_exceeded(self):
        with pytest.raises(CompileDeadlineExceeded):
            _call_with_deadline(lambda: time.sleep(2.0), 0.05)

    def test_builder_exception_relayed(self):
        with pytest.raises(RuntimeError, match="boom"):
            _call_with_deadline(
                lambda: (_ for _ in ()).throw(RuntimeError("boom")), 5.0
            )


class TestEagerExecution:
    def test_jit_passthrough_inside_context(self):
        def fn(x):
            return x + 1

        with eager_execution():
            assert eager_active()
            assert broker_jit(fn) is fn
        assert not eager_active()

    def test_thread_local(self):
        seen = {}

        def other():
            seen["eager"] = eager_active()

        with eager_execution():
            th = threading.Thread(target=other)
            th.start()
            th.join()
        assert seen["eager"] is False


class TestResilientLadder:
    def test_plain_path_behaves_like_get(self):
        broker = CompileBroker(speculative=False)
        info: dict = {}
        assert broker.get_resilient(("k",), lambda: "engine", info=info) == "engine"
        assert info["source"] == "miss"
        info = {}
        assert broker.get_resilient(("k",), lambda: pytest.fail("warm"), info=info) == (
            "engine"
        )
        assert info["source"] == "hit"
        assert broker.compile_misses == 1 and broker.compile_hits == 1

    def test_retry_then_success(self, monkeypatch):
        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        m = SchedulingMetrics()
        broker = CompileBroker(metrics=m, speculative=False)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient")
            return "engine"

        assert broker.get_resilient(("k",), flaky) == "engine"
        assert len(calls) == 3
        assert broker.compile_retries == 2
        phases = m.snapshot()["phases"]
        assert phases["compileRetries"] == 2
        assert phases["compileMisses"] == 1  # the eventual success

    def test_ladder_exhaustion_sets_cooldown(self, monkeypatch):
        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        monkeypatch.setenv("KSS_COMPILE_RETRIES", "1")
        monkeypatch.setenv("KSS_COMPILE_COOLDOWN_PASSES", "2")
        broker = CompileBroker(speculative=False)
        calls = []

        def failing():
            calls.append(1)
            raise RuntimeError("persistent")

        with pytest.raises(CompileUnavailable):
            broker.get_resilient(("k",), failing)
        assert len(calls) == 2  # 1 + KSS_COMPILE_RETRIES
        # cooldown: the next 2 calls degrade INSTANTLY (no build attempt)
        for _ in range(2):
            with pytest.raises(CompileUnavailable):
                broker.get_resilient(("k",), failing)
        assert len(calls) == 2
        # cooldown spent: the ladder re-probes — and can succeed
        assert broker.get_resilient(("k",), lambda: "healed") == "healed"

    def test_deadline_timeout_walks_the_ladder(self, monkeypatch):
        monkeypatch.setenv("KSS_COMPILE_DEADLINE_S", "0.05")
        monkeypatch.setenv("KSS_COMPILE_RETRIES", "1")
        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        broker = CompileBroker(speculative=False)
        with pytest.raises(CompileUnavailable, match="CompileDeadlineExceeded"):
            broker.get_resilient(("wedged",), lambda: time.sleep(1.0))
        assert broker.compile_retries == 1

    def test_stuck_abandoned_compile_blocks_reprobe(self, monkeypatch):
        """A watchdog-abandoned builder still inside XLA must block
        re-probing its key (each re-probe would leak another stuck
        thread); the key serves degraded until the old thread dies."""
        monkeypatch.setenv("KSS_COMPILE_DEADLINE_S", "0.05")
        monkeypatch.setenv("KSS_COMPILE_RETRIES", "0")
        monkeypatch.setenv("KSS_COMPILE_COOLDOWN_PASSES", "1")
        broker = CompileBroker(speculative=False)
        release = threading.Event()
        builds = []

        def wedged():
            builds.append(1)
            release.wait(10)
            return "late"

        with pytest.raises(CompileUnavailable):
            broker.get_resilient(("k",), wedged)
        assert len(builds) == 1
        # abandoned builders are keyed (scope, key) since the session
        # plane scoped the ladder state; scope None = sessionless caller
        th = broker._abandoned[(None, ("k",))][0]
        with pytest.raises(CompileUnavailable):
            broker.get_resilient(("k",), wedged)  # consumes the cooldown
        # the re-probe slot: refused — the abandoned builder is alive
        with pytest.raises(CompileUnavailable):
            broker.get_resilient(("k",), wedged)
        assert len(builds) == 1  # no second leaked thread
        release.set()
        th.join(5)
        with pytest.raises(CompileUnavailable):
            broker.get_resilient(("k",), wedged)  # the refusal's cooldown
        # stuck thread gone: the ladder re-probes — and can heal
        assert broker.get_resilient(("k",), lambda: "healed") == "healed"

    def test_injected_compile_slow_trips_watchdog(self, monkeypatch):
        monkeypatch.setenv("KSS_FAULT_INJECT", "compile_slow:200ms")
        monkeypatch.setenv("KSS_COMPILE_DEADLINE_S", "0.05")
        monkeypatch.setenv("KSS_COMPILE_RETRIES", "0")
        broker = CompileBroker(speculative=False)
        with pytest.raises(CompileUnavailable):
            broker.get_resilient(("k",), lambda: "engine")

    def test_expired_cooldown_reprobes_compile(self, monkeypatch):
        """A cooldown untouched past KSS_COMPILE_COOLDOWN_TTL_S expires:
        the next call of that scope re-probes the build (a returning
        tenant after a quiet spell gets a fresh compile attempt, and the
        stale entry stops degrading health())."""
        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        monkeypatch.setenv("KSS_COMPILE_RETRIES", "0")
        monkeypatch.setenv("KSS_COMPILE_COOLDOWN_PASSES", "100")
        monkeypatch.setenv("KSS_COMPILE_COOLDOWN_TTL_S", "0.05")
        broker = CompileBroker(speculative=False)
        with pytest.raises(CompileUnavailable):
            broker.get_resilient(
                ("k",), lambda: (_ for _ in ()).throw(RuntimeError("x"))
            )
        assert broker.health()["cooldownKeys"] == 1
        time.sleep(0.1)
        # the 100-pass cooldown would still be draining, but the TTL
        # expired it: health recovers and the next call builds
        assert broker.health()["cooldownKeys"] == 0
        assert broker.get_resilient(("k",), lambda: "engine") == "engine"

    def test_warm_hit_ends_cooldown(self, monkeypatch):
        monkeypatch.setenv("KSS_COMPILE_RETRIES", "0")
        monkeypatch.setenv("KSS_COMPILE_COOLDOWN_PASSES", "5")
        broker = CompileBroker(speculative=False)
        with pytest.raises(CompileUnavailable):
            broker.get_resilient(
                ("k",), lambda: (_ for _ in ()).throw(RuntimeError("x"))
            )
        # a background build lands the key warm mid-cooldown
        broker._background_build(("k",), lambda: "warm")
        assert broker.get_resilient(("k",), lambda: pytest.fail("warm")) == "warm"
        assert ("k",) not in broker._cooldown


class TestHardenedWorker:
    def test_crashed_task_disables_speculation_and_counts(self):
        m = SchedulingMetrics()
        broker = CompileBroker(metrics=m, speculative=True)

        def bad_task():
            raise RuntimeError("worker must not die silently")

        assert broker.speculate("t", bad_task)
        assert broker.drain(timeout=10)
        assert broker.worker_crashes == 1
        assert broker.speculative is False  # self-disabled
        assert not broker.speculate("t2", lambda: None)  # no new speculation
        assert m.snapshot()["phases"]["brokerWorkerCrashes"] == 1
        assert broker.stats()["brokerWorkerCrashes"] == 1

    def test_injected_worker_crash(self, monkeypatch):
        broker = CompileBroker(speculative=True)
        monkeypatch.setenv("KSS_FAULT_INJECT", "worker_crash:1.0")
        assert broker.speculate("t", lambda: pytest.fail("crashed before task"))
        assert broker.drain(timeout=10)
        assert broker.worker_crashes == 1
        assert broker.speculative is False

    def test_failed_background_build_is_not_a_crash(self):
        broker = CompileBroker(speculative=True)

        def task():
            return ("k",), lambda: (_ for _ in ()).throw(RuntimeError("compile"))

        assert broker.speculate("t", task)
        assert broker.drain(timeout=10)
        # a failed speculative COMPILE is a normal outcome: no crash,
        # speculation stays on
        assert broker.worker_crashes == 0
        assert broker.speculative is True

    def test_interpreter_exit_drains_inflight_speculation(self):
        """A speculative compile still inside XLA when the interpreter
        tears down aborts the process from XLA's C++ threads — the
        broker's atexit hook must out-wait it, so a SUCCEEDED run's
        process exits 0 (seen live as `--resume` exiting 134)."""
        import subprocess
        import sys
        import textwrap

        script = textwrap.dedent(
            """
            import jax, jax.numpy as jnp
            from kube_scheduler_simulator_tpu.utils.broker import CompileBroker

            broker = CompileBroker(speculative=True)

            def task():
                # a real lowering, large enough to still be compiling
                # when the main thread falls off the end of the script
                def build():
                    f = jax.jit(lambda x: jnp.linalg.matrix_power(x @ x.T, 8))
                    f(jnp.ones((200, 200))).block_until_ready()
                    return f
                return ("k",), build

            broker.speculate("t", task)
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr


def _cluster_service(metrics=None):
    store = ResourceStore()
    for i in range(4):
        store.apply("nodes", node(f"n{i}", cpu="16", mem="32Gi"))
    for i in range(5):
        store.apply("pods", pod(f"p{i}", cpu="100m"))
    metrics = metrics or SchedulingMetrics()
    return store, SchedulerService(store, metrics=metrics), metrics


class TestServiceEagerFallback:
    @pytest.mark.parametrize("mode", ["gang", "sequential"])
    def test_pass_completes_eagerly_under_persistent_compile_failure(
        self, monkeypatch, mode
    ):
        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        # healthy run first: the reference placements
        _, svc_ok, _ = _cluster_service()
        if mode == "gang":
            ok_placements, _, _ = svc_ok.schedule_gang(record=False)
        else:
            ok_placements = {
                (r.pod_namespace, r.pod_name): r.selected_node
                for r in svc_ok.schedule()
            }
        monkeypatch.setenv("KSS_FAULT_INJECT", "compile_fail:1.0")
        _, svc, metrics = _cluster_service()
        if mode == "gang":
            placements, _, _ = svc.schedule_gang(record=False)
        else:
            placements = {
                (r.pod_namespace, r.pod_name): r.selected_node
                for r in svc.schedule()
            }
        assert placements == ok_placements  # same pass, same answer
        phases = metrics.snapshot()["phases"]
        assert phases["degradedPasses"] >= 1
        assert phases["eagerFallbacks"] >= 1
        assert phases["compileRetries"] >= 1
        assert phases["compileMisses"] == 0  # nothing compiled

    def test_device_error_walks_the_execution_ladder(self, monkeypatch):
        """PR 4 semantics let an injected device_error propagate to the
        Abort path; the execution ladder (this PR, docs/resilience.md)
        now owns it: retried, mesh-shrunk, then failed over to CPU —
        the pass COMPLETES with the healthy run's placements."""
        _, svc_ok, _ = _cluster_service()
        ok_placements, _, _ = svc_ok.schedule_gang(record=False)
        monkeypatch.setenv("KSS_FAULT_INJECT", "device_error:1.0")
        _, svc, metrics = _cluster_service()
        placements, _, _ = svc.schedule_gang(record=False)
        assert placements == ok_placements
        assert svc.device_rung == "cpu"
        phases = metrics.snapshot()["phases"]
        assert phases["dispatchRetries"] >= 1
        assert phases["deviceFailovers"] == 1

    def test_record_mode_finish_stays_on_the_eager_rung(self, monkeypatch):
        """The gang record decode lazily jits its replay programs in
        `results()` — AFTER the eager-fallback build. With the compiler
        genuinely broken (jax.jit itself raises), the whole degraded
        pass, decode included, must still complete eagerly."""
        import jax

        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        monkeypatch.setenv("KSS_COMPILE_RETRIES", "0")
        _, svc_ok, _ = _cluster_service()
        ok_placements, ok_rounds, ok_results = svc_ok.schedule_gang(record=True)

        def broken_compiler(*_a, **_k):
            raise RuntimeError("XLA is down")

        monkeypatch.setattr(jax, "jit", broken_compiler)
        _, svc, metrics = _cluster_service()
        placements, rounds, results = svc.schedule_gang(record=True)
        assert placements == ok_placements
        assert rounds == ok_rounds
        assert [(r.pod_name, r.selected_node) for r in results] == [
            (r.pod_name, r.selected_node) for r in ok_results
        ]
        phases = metrics.snapshot()["phases"]
        assert phases["degradedPasses"] >= 1
        assert phases["eagerFallbacks"] >= 1

    def test_eager_failure_raises_engine_degraded(self, monkeypatch):
        monkeypatch.setenv("KSS_COMPILE_RETRIES", "0")
        _, svc, metrics = _cluster_service()

        def doomed():
            raise RuntimeError("no engine for you")

        with pytest.raises(EngineDegraded):
            try:
                svc.broker.get_resilient(("k",), doomed)
            except CompileUnavailable as e:
                svc._eager_fallback(doomed, e)
        assert metrics.snapshot()["phases"]["degradedPasses"] == 1
        assert metrics.snapshot()["phases"]["eagerFallbacks"] == 0


class TestHttpDegradation:
    @pytest.fixture()
    def server(self):
        server = SimulatorServer(SimulatorService(), port=0).start()
        yield server
        server.shutdown()

    def test_metrics_route_surfaces_resilience_counters(self, server):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/api/v1/metrics"
        ) as resp:
            doc = json.loads(resp.read())
        for key in (
            "degradedPasses",
            "compileRetries",
            "eagerFallbacks",
            "brokerWorkerCrashes",
        ):
            assert key in doc["phases"]

    def test_degradation_maps_to_503_with_retry_after(self, server, monkeypatch):
        monkeypatch.setattr(
            server.service.scheduler,
            "schedule",
            lambda: (_ for _ in ()).throw(
                EngineDegraded("compile ladder exhausted; eager failed")
            ),
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api/v1/schedule", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 503
        assert exc.value.headers["Retry-After"]
        body = json.loads(exc.value.read())
        assert body["kind"] == "EngineDegraded"
        assert "error" in body and "detail" in body

    def test_faulted_lifecycle_run_reports_degradation_in_metrics(
        self, server, monkeypatch
    ):
        """The acceptance criterion end-to-end: a chaos run POSTed with
        KSS_FAULT_INJECT forcing persistent compile failure still
        completes (eager fallback), and /api/v1/metrics reports
        degradedPasses > 0."""
        monkeypatch.setenv("KSS_FAULT_INJECT", "compile_fail:1.0")
        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        spec = {
            "name": "http-faulted",
            "seed": 3,
            "horizon": 6.0,
            "schedulerMode": "gang",
            "snapshot": {
                "nodes": [node(f"hn{i}", cpu="16", mem="32Gi") for i in range(2)]
            },
            "arrivals": [
                {
                    "kind": "trace",
                    "times": [1.0, 2.0, 3.0],
                    "template": {
                        "metadata": {"name": "hp"},
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "resources": {
                                        "requests": {
                                            "cpu": "100m", "memory": "64Mi",
                                        }
                                    },
                                }
                            ]
                        },
                    },
                }
            ],
        }
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api/v1/lifecycle",
            data=json.dumps(spec).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            result = json.loads(resp.read())
        assert result["phase"] == "Succeeded"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/api/v1/metrics"
        ) as resp:
            doc = json.loads(resp.read())
        assert doc["phases"]["degradedPasses"] > 0
        assert doc["phases"]["eagerFallbacks"] > 0

    def test_generic_500_is_structured(self, server, monkeypatch):
        monkeypatch.setattr(
            server.service.scheduler,
            "schedule",
            lambda: (_ for _ in ()).throw(RuntimeError("kaboom")),
        )
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api/v1/schedule", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req)
        assert exc.value.code == 500
        body = json.loads(exc.value.read())
        assert body["kind"] == "RuntimeError"
        assert "kaboom" in body["error"]
        assert body["message"] == body["error"]  # back-compat mirror
