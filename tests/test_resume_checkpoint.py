"""Crash-safe run supervision: checkpoint/resume parity (the robustness
PR's tentpole acceptance criteria).

* A lifecycle chaos run stopped mid-horizon (the deterministic
  mid-run-kill stand-in `stop_after_events`) and resumed from its
  checkpoint produces a JSONL trace byte-identical to the uninterrupted
  run — asserted for gang + sequential modes and for BOTH the sync and
  async pipelines (concatenated prefix+suffix AND the resumed engine's
  full trace).
* Periodic checkpoints fire on the events/sim-seconds cadence, land
  atomically, and any of them resumes correctly.
* `ResourceStore.dump_state`/`load_state` and `ChaosSpec.to_dict` are
  exact round trips — the two legs the checkpoint format stands on.
* A run resumed under `KSS_FAULT_INJECT` compile failure still converges
  byte-identically via the eager fallback (resume-after-kill × the
  degradation ladder).
"""

from __future__ import annotations

import json
import os

import pytest

from kube_scheduler_simulator_tpu.lifecycle.checkpoint import (
    CHECKPOINT_FORMAT,
    load_checkpoint,
    write_checkpoint,
)
from kube_scheduler_simulator_tpu.lifecycle.engine import (
    LifecycleEngine,
    trace_jsonl,
)
from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec

from helpers import node, pod


def _chaos_dict(mode: str, pipeline: str) -> dict:
    # same snapshot shapes as tests/test_async_pipeline.py so the
    # compiled programs come warm from the shared persistent cache
    nodes = [node(f"n{i}", cpu="16", mem="32Gi", pods="110") for i in range(6)]
    pods = [
        pod(f"seed-{i}", cpu="100m", node_name=f"n{i % 6}") for i in range(33)
    ]
    return {
        "name": "ckpt",
        "seed": 11,
        "horizon": 30.0,
        "schedulerMode": mode,
        "pipeline": pipeline,
        "snapshot": {"nodes": nodes, "pods": pods},
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 0.5,
                "count": 10,
                "template": {
                    "metadata": {"name": "churn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
        # binding-reading faults: evictions + re-enqueues live across
        # the checkpoint boundary (the _downed/_evicted_at state legs)
        "faults": [
            {"at": 8.0, "action": "cordon", "node": "n0"},
            {"at": 14.0, "action": "fail", "node": "n1"},
            {"at": 20.0, "action": "recover", "node": "n1"},
            {"at": 26.0, "action": "uncordon", "node": "n0"},
        ],
    }


def _spec(mode: str, pipeline: str) -> ChaosSpec:
    return ChaosSpec.from_dict(_chaos_dict(mode, pipeline))


# one uninterrupted baseline trace per scheduler mode (sync/async traces
# are already pinned byte-identical by tests/test_async_pipeline.py)
_BASELINES: dict = {}


def _baseline_trace(mode: str) -> str:
    if mode not in _BASELINES:
        eng = LifecycleEngine(_spec(mode, "sync"))
        res = eng.run()
        assert res["phase"] == "Succeeded"
        _BASELINES[mode] = eng.trace_jsonl()
    return _BASELINES[mode]


class TestKillAndResumeParity:
    @pytest.mark.parametrize("mode", ["gang", "sequential"])
    @pytest.mark.parametrize("pipeline", ["sync", "async"])
    def test_concatenated_trace_byte_identical(self, tmp_path, mode, pipeline):
        baseline = _baseline_trace(mode)
        ckpt = str(tmp_path / "run.ckpt.json")

        eng = LifecycleEngine(
            _spec(mode, pipeline), checkpoint_path=ckpt, stop_after_events=7
        )
        res = eng.run()
        assert res["phase"] == "Interrupted"
        assert res["checkpoint"] == ckpt
        assert eng.events_consumed == 7
        # the interrupted trace is an exact PREFIX (nothing extra emitted)
        assert baseline.startswith(eng.trace_jsonl())

        doc = load_checkpoint(ckpt)
        assert doc["format"] == CHECKPOINT_FORMAT
        assert doc["cursor"] == 7
        # the checkpointed prefix and its advertised byte offset agree
        prefix = trace_jsonl(doc["trace"])
        assert len(prefix.encode()) == doc["traceByteOffset"]

        resumed = LifecycleEngine.from_checkpoint(doc)
        assert resumed.pipeline == pipeline  # sticky across resume
        res2 = resumed.run()
        assert res2["phase"] == "Succeeded"
        assert res2["resumed"] == {
            "cursor": 7,
            "traceEvents": len(doc["trace"]),
        }
        # the tentpole contract, both ways of reading it: checkpointed
        # prefix + resumed suffix, and the resumed engine's full trace
        suffix = resumed.trace_jsonl_since(resumed.resume_trace_index)
        assert prefix + suffix == baseline
        assert resumed.trace_jsonl() == baseline

    def test_resumed_metrics_cover_the_whole_run(self, tmp_path):
        ckpt = str(tmp_path / "run.ckpt.json")
        full = LifecycleEngine(_spec("gang", "sync"))
        rf = full.run()
        assert rf["phase"] == "Succeeded"

        eng = LifecycleEngine(
            _spec("gang", "sync"), checkpoint_path=ckpt, stop_after_events=7
        )
        eng.run()
        resumed = LifecycleEngine.from_checkpoint(load_checkpoint(ckpt))
        r2 = resumed.run()
        # cumulative deterministic counters carried through the
        # checkpoint: the resumed run reports the WHOLE run
        for key in ("totalPods", "totalScheduled", "passes"):
            assert r2["metrics"][key] == rf["metrics"][key]
        assert r2["metrics"]["disruption"] == rf["metrics"]["disruption"]
        assert r2["pods"] == rf["pods"]

    def test_histogram_state_survives_the_checkpoint(self, tmp_path):
        """Telemetry continuity (the observability PR's satellite): the
        checkpoint carries `SchedulingMetrics` histogram state, so a
        resumed run's latency distributions cover the WHOLE run. Bucket
        placement of wall-clock histograms isn't deterministic, so the
        parity assertions stick to deterministic quantities: observation
        counts, and the sim-time time-to-reschedule family exactly."""
        ckpt = str(tmp_path / "hist.ckpt.json")
        full = LifecycleEngine(_spec("gang", "sync"))
        rf = full.run()
        assert rf["phase"] == "Succeeded"

        eng = LifecycleEngine(
            _spec("gang", "sync"), checkpoint_path=ckpt, stop_after_events=7
        )
        eng.run()
        doc = load_checkpoint(ckpt)
        # the checkpoint itself carries the histogram block, and the
        # prefix's pass latencies are already in it
        assert set(doc["metrics"]["_histograms"]) == {
            "passLatencySeconds",
            "compileStallSeconds",
            "timeToRescheduleSeconds",
        }
        prefix_hist = doc["metrics"]["_histograms"]["passLatencySeconds"]
        assert 0 < prefix_hist["count"] == doc["metrics"]["_pass_count"]

        resumed = LifecycleEngine.from_checkpoint(doc)
        r2 = resumed.run()
        assert r2["phase"] == "Succeeded"
        h_full, h_res = rf["metrics"]["histograms"], r2["metrics"]["histograms"]
        # one latency observation per pass, prefix + suffix = whole run
        assert (
            h_res["passLatencySeconds"]["count"]
            == h_full["passLatencySeconds"]["count"]
            == rf["metrics"]["passes"]
        )
        # sim-time distribution is deterministic: exact bucket parity
        assert h_res["timeToRescheduleSeconds"] == h_full["timeToRescheduleSeconds"]


class TestPeriodicCheckpoints:
    def test_event_cadence_and_any_checkpoint_resumes(self, tmp_path):
        baseline = _baseline_trace("gang")
        ckpt = str(tmp_path / "periodic.ckpt.json")
        eng = LifecycleEngine(
            _spec("gang", "sync"), checkpoint_path=ckpt,
            checkpoint_every_events=4,
        )
        res = eng.run()
        assert res["phase"] == "Succeeded"
        assert eng.checkpoints_written >= 2
        # the last periodic checkpoint (whatever batch boundary it hit)
        # resumes to the same bytes
        doc = eng.last_checkpoint_doc
        assert 0 < doc["cursor"] <= eng.events_consumed
        resumed = LifecycleEngine.from_checkpoint(doc)
        assert resumed.run()["phase"] == "Succeeded"
        assert resumed.trace_jsonl() == baseline

    def test_sim_seconds_cadence(self, tmp_path):
        ckpt = str(tmp_path / "simcadence.ckpt.json")
        eng = LifecycleEngine(
            _spec("gang", "sync"), checkpoint_path=ckpt,
            checkpoint_every_sim_s=10.0,
        )
        assert eng.run()["phase"] == "Succeeded"
        # 30s horizon / 10s cadence: at least two fired
        assert eng.checkpoints_written >= 2

    def test_request_stop_is_graceful(self, tmp_path):
        """The SIGINT/SIGTERM path: stop lands at a batch boundary with
        a final checkpoint and an exactly-prefix trace."""
        baseline = _baseline_trace("gang")
        ckpt = str(tmp_path / "stop.ckpt.json")
        eng = LifecycleEngine(_spec("gang", "sync"), checkpoint_path=ckpt)
        eng.request_stop()  # before run: stops after the FIRST batch
        res = eng.run()
        assert res["phase"] == "Interrupted"
        assert os.path.exists(ckpt)
        assert baseline.startswith(eng.trace_jsonl())
        resumed = LifecycleEngine.from_checkpoint(load_checkpoint(ckpt))
        assert resumed.run()["phase"] == "Succeeded"
        assert resumed.trace_jsonl() == baseline


class TestCheckpointFormat:
    def test_atomic_write_and_validation(self, tmp_path):
        path = str(tmp_path / "x.json")
        with pytest.raises(FileNotFoundError):
            load_checkpoint(path)
        write_checkpoint({"format": "wrong"}, path)
        with pytest.raises(
            ValueError, match="not a checkpoint of the expected kind"
        ):
            load_checkpoint(path)
        # no torn temp files left behind
        assert os.listdir(tmp_path) == ["x.json"]

    def test_checkpoint_is_json_serializable_end_to_end(self, tmp_path):
        ckpt = str(tmp_path / "roundtrip.ckpt.json")
        eng = LifecycleEngine(
            _spec("gang", "sync"), checkpoint_path=ckpt, stop_after_events=7
        )
        eng.run()
        # a full JSON round trip (what a real kill/restart does) loses
        # nothing the resume needs
        doc = json.loads(json.dumps(load_checkpoint(ckpt)))
        resumed = LifecycleEngine.from_checkpoint(doc)
        assert resumed.run()["phase"] == "Succeeded"
        assert resumed.trace_jsonl() == _baseline_trace("gang")


class TestStoreStateRoundtrip:
    def test_dump_load_preserves_objects_and_order(self):
        store = ResourceStore()
        store.apply("nodes", node("b"))
        store.apply("nodes", node("a"))
        store.apply("pods", pod("p1", node_name="b"))
        store.delete("nodes", "b")  # cascades p1 away
        store.apply("nodes", node("b"))  # re-added: moves to the END
        dump = json.loads(json.dumps(store.dump_state()))

        restored = ResourceStore()
        restored.load_state(dump)
        # objects verbatim (rv/uid included), iteration order preserved
        assert [n["metadata"]["name"] for n in restored.list("nodes")] == [
            "a", "b",
        ]
        assert restored.list("nodes") == store.list("nodes")
        assert restored.count("pods") == 0
        # the rv counter resumes PAST the dump: no rv reuse
        before = store.latest_rv()
        assert restored.latest_rv() == before
        obj = restored.apply("nodes", node("c"))
        assert int(obj["metadata"]["resourceVersion"]) == before + 1

    def test_restore_is_a_relist_boundary(self):
        from kube_scheduler_simulator_tpu.models.store import (
            StaleResourceVersion,
        )

        store = ResourceStore()
        store.apply("nodes", node("a"))
        restored = ResourceStore()
        restored.load_state(store.dump_state())
        # incremental consumers must relist: their window predates the
        # restored log (which starts empty at the dump's high-water rv)
        with pytest.raises(StaleResourceVersion):
            restored.events_since("nodes", 0)


class TestSpecRoundtrip:
    @pytest.mark.parametrize("mode", ["gang", "sequential"])
    def test_to_dict_reparses_to_the_same_timeline(self, mode):
        spec = _spec(mode, "async")
        again = ChaosSpec.from_dict(spec.to_dict())
        assert again == spec
        assert again.events() == spec.events()

    def test_all_arrival_kinds_and_taints_roundtrip(self):
        d = {
            "seed": 3,
            "horizon": 50.0,
            "window": 4,
            "arrivals": [
                {"kind": "poisson", "rate": 1.0, "count": 5,
                 "template": {"metadata": {"name": "poi"}}},
                {"kind": "trace", "times": [1.0, 2.5],
                 "template": {"metadata": {"name": "tra"}}},
                {"kind": "gang", "at": 3.0, "replicas": 4,
                 "template": {"metadata": {"name": "gan"}}},
            ],
            "faults": [
                {"at": 5.0, "action": "taint", "node": "n0",
                 "taint": {"key": "k", "value": "v", "effect": "NoSchedule"}},
            ],
        }
        spec = ChaosSpec.from_dict(d)
        again = ChaosSpec.from_dict(json.loads(json.dumps(spec.to_dict())))
        assert again == spec
        assert again.events() == spec.events()


class TestResumeUnderFaultInjection:
    def test_resume_after_kill_with_persistent_compile_failure(
        self, tmp_path, monkeypatch
    ):
        """Resume-after-kill × the degradation ladder (the acceptance
        criterion): with KSS_FAULT_INJECT forcing every compile to fail,
        the interrupted-and-resumed run still completes via the eager
        fallback, byte-identical, with degradedPasses > 0."""
        baseline = _baseline_trace("gang")
        monkeypatch.setenv("KSS_FAULT_INJECT", "compile_fail:1.0")
        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        ckpt = str(tmp_path / "faulted.ckpt.json")
        eng = LifecycleEngine(
            _spec("gang", "sync"), checkpoint_path=ckpt, stop_after_events=7
        )
        assert eng.run()["phase"] == "Interrupted"
        resumed = LifecycleEngine.from_checkpoint(load_checkpoint(ckpt))
        res = resumed.run()
        assert res["phase"] == "Succeeded"
        assert resumed.trace_jsonl() == baseline
        assert res["metrics"]["phases"]["degradedPasses"] > 0
        assert res["metrics"]["phases"]["eagerFallbacks"] > 0
