"""KEP-140 scenario VM: operations at MajorStep boundaries, controllers +
scheduler to fixpoint between them, deterministic Timeline."""

from kube_scheduler_simulator_tpu.scenario import Operation, ScenarioRunner

from helpers import node, pod
from test_controllers import deployment


def make_ops():
    return [
        Operation(major_step=1, create={"kind": "nodes", "object": node("n0")}),
        Operation(major_step=1, create={"kind": "nodes", "object": node("n1")}),
        Operation(
            major_step=1,
            create={"kind": "deployments", "object": deployment("web", 3)},
        ),
        Operation(
            major_step=2,
            patch={
                "kind": "deployments",
                "name": "web",
                "namespace": "default",
                "patch": {"spec": {"replicas": 1}},
            },
        ),
        Operation(major_step=3, delete={"kind": "nodes", "name": "n1"}),
        Operation(major_step=3, done=True),
    ]


class TestScenarioVM:
    def test_full_lifecycle(self):
        result = ScenarioRunner(make_ops()).run()
        assert result.phase == "Succeeded", result.message
        t = result.timeline
        # step 1: 3 creates + replicaset expansion + 3 PodScheduled events
        types1 = [e.type for e in t["1"]]
        assert types1.count("Create") == 3
        assert types1.count("PodScheduled") == 3
        # minor steps strictly increase within the major step
        minors = [e.step.minor for e in t["1"]]
        assert minors == sorted(minors) and len(set(minors)) == len(minors)
        # step 2: scale-down deletes pods, nothing new scheduled
        assert not any(e.type == "PodScheduled" for e in t["2"])
        # step 3: node delete cascades; the surviving pod count is 1
        assert any(e.type == "Done" for e in t["3"])

    def test_determinism_bit_identical(self):
        a = ScenarioRunner(make_ops()).run().as_dict()
        b = ScenarioRunner(make_ops()).run().as_dict()
        # strip resourceVersions/uids? No — identical runs must produce
        # identical versions too (same op order, same store).
        assert a == b

    def test_paused_without_done(self):
        ops = [
            Operation(major_step=1, create={"kind": "nodes", "object": node("n0")}),
        ]
        result = ScenarioRunner(ops).run()
        assert result.phase == "Paused"

    def test_failed_on_bad_delete(self):
        ops = [
            Operation(major_step=1, delete={"kind": "pods", "name": "ghost"}),
        ]
        result = ScenarioRunner(ops).run()
        assert result.phase == "Failed"
        assert "ghost" in result.message

    def test_invalid_operation_rejected(self):
        import pytest

        op = Operation(major_step=1)
        with pytest.raises(ValueError):
            op.validate()
        op2 = Operation(
            major_step=1,
            create={"kind": "nodes", "object": node("x")},
            done=True,
        )
        with pytest.raises(ValueError):
            op2.validate()

    def test_scheduler_is_a_simulation_controller(self):
        # pods created directly (no deployment) are scheduled in step 2
        ops = [
            Operation(major_step=1, create={"kind": "nodes", "object": node("n0")}),
            Operation(major_step=2, create={"kind": "pods", "object": pod("p0")}),
            Operation(major_step=2, done=True),
        ]
        result = ScenarioRunner(ops).run()
        assert result.phase == "Succeeded"
        sched_events = [
            e for e in result.timeline["2"] if e.type == "PodScheduled"
        ]
        assert len(sched_events) == 1
        assert sched_events[0].payload["node"] == "n0"

    def test_preemption_records_delete_event(self):
        ops = [
            Operation(
                major_step=1,
                create={"kind": "nodes", "object": node("only", cpu="1")},
            ),
            Operation(
                major_step=1,
                create={
                    "kind": "pods",
                    "object": pod("squatter", cpu="800m", priority=1),
                },
            ),
            Operation(
                major_step=2,
                create={
                    "kind": "pods",
                    "object": pod("urgent", cpu="800m", priority=100),
                },
            ),
            Operation(major_step=2, done=True),
        ]
        result = ScenarioRunner(ops).run()
        assert result.phase == "Succeeded", result.message
        t2 = result.timeline["2"]
        deletes = [e for e in t2 if e.type == "Delete"]
        assert any(e.payload.get("name") == "squatter" for e in deletes)
        scheduled = [e for e in t2 if e.type == "PodScheduled"]
        assert any(e.payload["name"] == "urgent" for e in scheduled)


def test_gang_scheduler_mode_timeline():
    from kube_scheduler_simulator_tpu.scenario.runner import (
        Operation,
        ScenarioRunner,
    )

    ops = [
        Operation(major_step=0, create={"kind": "nodes", "object": node("n0")}),
        Operation(major_step=0, create={"kind": "nodes", "object": node("n1")}),
        Operation(major_step=0, create={"kind": "pods", "object": pod("a")}),
        Operation(major_step=0, create={"kind": "pods", "object": pod("b")}),
        Operation(major_step=1, done=True),
    ]
    result = ScenarioRunner(ops, scheduler_mode="gang").run()
    assert result.phase == "Succeeded"
    scheduled = [
        e for e in result.timeline["0"] if e.type == "PodScheduled"
    ]
    assert {e.payload["name"] for e in scheduled} == {"a", "b"}
    assert all(e.payload["node"] for e in scheduled)
    # determinism: a second run produces the identical timeline
    again = ScenarioRunner(
        [Operation(**{k: getattr(o, k) for k in
                      ("id", "major_step", "create", "patch", "delete", "done")})
         for o in ops],
        scheduler_mode="gang",
    ).run()
    assert again.as_dict() == result.as_dict()


def test_gang_scheduler_mode_records_preemption_deletes():
    """Gang mode's preempt phase evicts pre-bound victims; the Timeline
    must carry the same Delete(reason=preempted) events the sequential
    branch records, so the Timeline reconciles with the final store."""
    from kube_scheduler_simulator_tpu.scenario.runner import (
        Operation,
        ScenarioRunner,
    )

    ops = [
        Operation(
            major_step=1,
            create={"kind": "nodes", "object": node("only", cpu="1")},
        ),
        Operation(
            major_step=1,
            create={
                "kind": "pods",
                "object": pod("squatter", cpu="800m", priority=1),
            },
        ),
        Operation(
            major_step=2,
            create={
                "kind": "pods",
                "object": pod("urgent", cpu="800m", priority=100),
            },
        ),
        Operation(major_step=2, done=True),
    ]
    runner = ScenarioRunner(ops, scheduler_mode="gang")
    result = runner.run()
    assert result.phase == "Succeeded", result.message
    t2 = result.timeline["2"]
    deletes = [e for e in t2 if e.type == "Delete"]
    assert any(
        e.payload.get("name") == "squatter"
        and e.payload.get("reason") == "preempted"
        for e in deletes
    )
    scheduled = [e for e in t2 if e.type == "PodScheduled"]
    assert any(e.payload["name"] == "urgent" for e in scheduled)
    # the store agrees with the Timeline
    assert runner.store.get("pods", "squatter") is None
    assert runner.store.get("pods", "urgent")["spec"]["nodeName"] == "only"


def test_summarize_result_calculation():
    from kube_scheduler_simulator_tpu.scenario import summarize
    from kube_scheduler_simulator_tpu.scenario.runner import (
        Operation,
        ScenarioRunner,
    )

    ops = [
        Operation(major_step=0, create={"kind": "nodes", "object": node("n0", cpu="2")}),
        Operation(major_step=0, create={"kind": "pods",
                                        "object": pod("early", cpu="500m")}),
        Operation(major_step=2, create={"kind": "pods",
                                        "object": pod("late", cpu="500m")}),
        Operation(major_step=2, create={"kind": "pods",
                                        "object": pod("toobig", cpu="8")}),
        Operation(major_step=3, done=True),
    ]
    runner = ScenarioRunner(ops)
    result = runner.run()
    s = summarize(result, runner.store)
    assert s["phase"] == "Succeeded"
    assert s["pods"] == {"scheduled": 2, "preempted": 0, "pending": 1}
    assert s["bindLatencySteps"] == {"max": 0, "mean": 0.0}  # bound same step
    assert s["perStep"]["0"]["binds"] == 1
    assert s["perStep"]["2"]["binds"] == 1
    n0 = s["nodes"]["n0"]
    assert n0["pods"] == 2 and abs(n0["cpuUtilization"] - 0.5) < 1e-9


def test_pre_simulation_controllers_settle_imported_state():
    from kube_scheduler_simulator_tpu.models.store import ResourceStore
    from kube_scheduler_simulator_tpu.scenario.runner import (
        Operation,
        ScenarioRunner,
    )

    store = ResourceStore()
    store.apply("nodes", node("n0"))
    store.apply(
        "deployments",
        {
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {
                "replicas": 3,
                "selector": {"matchLabels": {"app": "web"}},
                "template": {
                    "metadata": {"labels": {"app": "web"}},
                    "spec": {"containers": [{"name": "c", "resources":
                             {"requests": {"cpu": "100m", "memory": "64Mi"}}}]},
                },
            },
        },
    )
    ops = [Operation(major_step=0, done=True)]
    result = ScenarioRunner(ops, store=store, pre_simulation=True).run()
    assert result.phase == "Succeeded"
    # deployment expanded BEFORE step 0 (no Create events in the timeline
    # for the replicas), then the step-0 controller round scheduled them
    assert len(store.list("pods")) == 3
    creates = [e for e in result.timeline["0"] if e.type == "Create"]
    assert not creates
    scheduled = [e for e in result.timeline["0"] if e.type == "PodScheduled"]
    assert len(scheduled) == 3


def test_summarize_counts_deleted_nondefault_namespace_pod():
    from kube_scheduler_simulator_tpu.scenario import summarize
    from kube_scheduler_simulator_tpu.scenario.runner import (
        Operation,
        ScenarioRunner,
    )

    p = pod("web-1", ns="team-a")
    ops = [
        Operation(major_step=0, create={"kind": "nodes", "object": node("n0")}),
        Operation(major_step=0, create={"kind": "pods", "object": p}),
        Operation(major_step=1, delete={"kind": "pods", "name": "web-1",
                                        "namespace": "team-a"}),
        Operation(major_step=2, done=True),
    ]
    runner = ScenarioRunner(ops)
    result = runner.run()
    s = summarize(result, runner.store)
    # bound at step 0, deleted at step 1: not scheduled in the end state
    assert s["pods"]["scheduled"] == 0
    assert s["pods"]["pending"] == 0
