"""Randomized scenario-VM fuzz: KEP-140's determinism requirement.

KEP-140 names determinism as a core design constraint (same scenario →
same result; keps/140-scenario-based-simulation/README.md:329-330,
:439-445). Directed scenario tests live in test_scenario.py; this fuzz
generates random operation scripts — node/pod creates with mixed sizes
and priorities, deletes, deployments (controller expansion), pod churn
across major steps — and checks, per seed:

  * running the identical spec twice produces identical result
    documents (timeline, placements, summary) — the determinism pin;
  * the timeline's (major, minor) clock never goes backwards;
  * every bind in the timeline targets a node that existed at that
    step, and every bound pod fits its node's pod-count allocatable
    (capacity safety reconstructed from the script, not the engine).
"""

import random

import pytest

from kube_scheduler_simulator_tpu.scenario.batch import _op_from_dict
from kube_scheduler_simulator_tpu.scenario.runner import ScenarioRunner


def _spec(rng: random.Random) -> dict:
    ops = []
    n_nodes = rng.randint(2, 5)
    for i in range(n_nodes):
        ops.append(
            {
                "majorStep": 0,
                "create": {
                    "kind": "nodes",
                    "object": {
                        "metadata": {"name": f"n{i}"},
                        "status": {
                            "allocatable": {
                                "cpu": str(rng.choice((1, 2, 4))),
                                "memory": "8Gi",
                                "pods": str(rng.randint(4, 12)),
                            }
                        },
                    },
                },
            }
        )
    pod_id = 0
    for step in range(rng.randint(1, 4)):
        for _ in range(rng.randint(1, 6)):
            r = rng.random()
            if r < 0.7 or pod_id == 0:
                ops.append(
                    {
                        "majorStep": step,
                        "create": {
                            "kind": "pods",
                            "object": {
                                "metadata": {"name": f"p{pod_id}"},
                                "spec": {
                                    "priority": rng.choice((0, 10, 1000)),
                                    "containers": [
                                        {
                                            "name": "c",
                                            "resources": {
                                                "requests": {
                                                    "cpu": f"{rng.randint(100, 1200)}m",
                                                    "memory": "256Mi",
                                                }
                                            },
                                        }
                                    ],
                                },
                            },
                        },
                    }
                )
                pod_id += 1
            elif r < 0.85 and pod_id > 0:
                ops.append(
                    {
                        "majorStep": step,
                        "delete": {
                            "kind": "pods",
                            "name": f"p{rng.randint(0, pod_id - 1)}",
                        },
                    }
                )
            else:
                ops.append(
                    {
                        "majorStep": step,
                        "create": {
                            "kind": "deployments",
                            "object": {
                                "metadata": {"name": f"d{step}-{pod_id}"},
                                "spec": {
                                    "replicas": rng.randint(1, 3),
                                    "selector": {
                                        "matchLabels": {"app": f"d{step}"}
                                    },
                                    "template": {
                                        "metadata": {
                                            "labels": {"app": f"d{step}"}
                                        },
                                        "spec": {
                                            "containers": [
                                                {
                                                    "name": "c",
                                                    "resources": {
                                                        "requests": {
                                                            "cpu": "100m",
                                                            "memory": "64Mi",
                                                        }
                                                    },
                                                }
                                            ]
                                        },
                                    },
                                },
                            },
                        },
                    }
                )
        last = step
    ops.append({"majorStep": last, "done": True})
    return {"kind": "scenario", "operations": ops}


@pytest.mark.parametrize("seed", [41, 42, 43, 44])
def test_fuzz_scenario_determinism_and_clock(seed):
    rng = random.Random(seed)
    spec = _spec(rng)

    def run():
        ops = [
            _op_from_dict(d, i)
            for i, d in enumerate(spec["operations"])
        ]
        return ScenarioRunner(ops).run().as_dict()

    a, b = run(), run()
    assert a == b, "scenario VM must be deterministic"
    assert a["phase"] in ("Succeeded", "Paused"), a["message"]

    # flatten the {majorStr: [events]} Timeline in step order
    events = []
    for major in sorted(a["timeline"], key=int):
        events.extend(a["timeline"][major])

    # virtual clock monotone
    clock = [(ev["step"]["major"], ev["step"]["minor"]) for ev in events]
    assert clock == sorted(clock), "ScenarioStep went backwards"

    # capacity safety from the script's own numbers: replay PodScheduled
    # / Delete events into final placements, per-node count <= the
    # node's declared pods allocatable
    caps = {}
    for op in spec["operations"]:
        c = op.get("create")
        if c and c["kind"] == "nodes":
            caps[c["object"]["metadata"]["name"]] = int(
                c["object"]["status"]["allocatable"]["pods"]
            )
    placed = {}
    for ev in events:
        p = ev["payload"]
        if ev["type"] == "PodScheduled":
            placed[(p["namespace"], p["name"])] = p["node"]
        elif ev["type"] == "Delete" and p.get("kind") == "pods":
            placed.pop((p.get("namespace", "default"), p["name"]), None)
    per_node = {}
    for node in placed.values():
        per_node[node] = per_node.get(node, 0) + 1
    for node, cnt in per_node.items():
        assert cnt <= caps[node], (node, cnt, caps[node])
    # something actually scheduled in every generated scenario
    assert any(ev["type"] == "PodScheduled" for ev in events)
