"""POST /api/v1/scenario — the KEP-140 scenario VM / KEP-159 sweep
runner exposed through the serving shell (isolated store per run)."""

import json
import urllib.error
import urllib.request

from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import SimulatorService

from helpers import node, pod


def _post(url, payload):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        return resp.status, json.loads(resp.read())


class TestScenarioRoute:
    def setup_method(self):
        self.server = SimulatorServer(SimulatorService(), port=0).start()
        self.base = f"http://127.0.0.1:{self.server.port}/api/v1"

    def teardown_method(self):
        self.server.shutdown()

    def test_scenario_run_returns_timeline_and_summary(self):
        spec = {
            "kind": "scenario",
            "operations": [
                {"majorStep": 0, "create": {"kind": "nodes", "object": node("n0")}},
                {"majorStep": 0, "create": {"kind": "pods", "object": pod("p0")}},
                {"majorStep": 1, "done": True},
            ],
        }
        st, out = _post(f"{self.base}/scenario", spec)
        assert st == 200
        assert out["phase"] == "Succeeded"
        events = out["timeline"]["0"]
        assert any(e["type"] == "PodScheduled" for e in events)
        assert out["summary"]["pods"]["scheduled"] == 1
        # isolation: the server's own store saw nothing
        with urllib.request.urlopen(f"{self.base}/resources/pods") as resp:
            assert json.load(resp)["items"] == []

    def test_sweep_run_over_http(self):
        spec = {
            "kind": "sweep",
            "snapshot": {
                "nodes": [node("n0"), node("n1")],
                "pods": [pod("a"), pod("b")],
            },
            "weightVariants": [{}, {"NodeResourcesFit": 5}],
        }
        st, out = _post(f"{self.base}/scenario", spec)
        assert st == 200
        assert out["phase"] == "Succeeded"
        assert len(out["variants"]) == 2
        for v in out["variants"]:
            assert v["scheduled"] == 2

    def test_bad_spec_is_400(self):
        try:
            _post(f"{self.base}/scenario", {"kind": "nope"})
            raise AssertionError("accepted bad kind")
        except urllib.error.HTTPError as e:
            assert e.code == 400
