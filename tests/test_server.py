"""HTTP-level tests for the serving shell: every reference route
(simulator/server/server.go:42-57) round-trips against a live server."""

import json
import threading
import time
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService

from helpers import node, pod


def _req(port, method, path, body=None, timeout=300):
    # generous timeout: a schedule pass may pay a fresh XLA compile, which
    # can take minutes on a loaded CPU test machine
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None


@pytest.fixture()
def server():
    srv = SimulatorServer(SimulatorService(), port=0).start()
    yield srv
    srv.shutdown()


class TestSchedulerConfigRoutes:
    def test_get_returns_default(self, server):
        code, cfg = _req(server.port, "GET", "/api/v1/schedulerconfiguration")
        assert code == 200
        assert cfg["profiles"][0]["schedulerName"] == "default-scheduler"

    def test_post_restarts_and_get_roundtrips(self, server):
        newcfg = {
            "profiles": [
                {
                    "schedulerName": "default-scheduler",
                    "plugins": {
                        "score": {
                            "disabled": [{"name": "*"}],
                            "enabled": [{"name": "NodeResourcesFit", "weight": 5}],
                        }
                    },
                }
            ]
        }
        code, _ = _req(
            server.port, "POST", "/api/v1/schedulerconfiguration", newcfg
        )
        assert code == 202
        code, got = _req(server.port, "GET", "/api/v1/schedulerconfiguration")
        assert code == 200
        assert got["profiles"][0]["plugins"]["score"]["enabled"] == [
            {"name": "NodeResourcesFit", "weight": 5}
        ]

    def test_post_invalid_config_rolls_back(self, server):
        bad = {
            "profiles": [
                {
                    "schedulerName": "default-scheduler",
                    "plugins": {
                        "filter": {
                            "disabled": [{"name": "*"}],
                            "enabled": [{"name": "NoSuchPlugin"}],
                        }
                    },
                }
            ]
        }
        code, err = _req(
            server.port, "POST", "/api/v1/schedulerconfiguration", bad
        )
        assert code == 500
        assert "NoSuchPlugin" in err["message"]
        # old config still served (rollback, scheduler.go:70-87)
        code, got = _req(server.port, "GET", "/api/v1/schedulerconfiguration")
        assert code == 200
        assert "NoSuchPlugin" not in json.dumps(got)


class TestResourceAndScheduleRoutes:
    def test_crud_schedule_writeback(self, server):
        p = server.port
        code, _ = _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))
        assert code == 201
        code, _ = _req(p, "PUT", "/api/v1/resources/nodes", node("n1"))
        assert code == 201
        code, _ = _req(p, "PUT", "/api/v1/resources/pods", pod("web"))
        assert code == 201

        code, out = _req(p, "POST", "/api/v1/schedule")
        assert code == 200
        assert out["scheduled"] == 1
        assert out["results"][0]["status"] == "Scheduled"

        # write-back: nodeName + the 13 annotations on the pod object
        code, got = _req(p, "GET", "/api/v1/resources/pods/default/web")
        assert code == 200
        assert got["spec"]["nodeName"] in ("n0", "n1")
        ann = got["metadata"]["annotations"]
        assert got["spec"]["nodeName"] == ann["scheduler-simulator/selected-node"]
        filter_result = json.loads(ann["scheduler-simulator/filter-result"])
        assert set(filter_result) == {"n0", "n1"}

    def test_delete_node_cascades(self, server):
        p = server.port
        _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))
        _req(p, "PUT", "/api/v1/resources/pods", pod("w", node_name="n0"))
        code, _ = _req(p, "DELETE", "/api/v1/resources/nodes/n0")
        assert code == 200
        code, items = _req(p, "GET", "/api/v1/resources/pods")
        assert items["items"] == []

    def test_unknown_kind_404(self, server):
        code, _ = _req(server.port, "GET", "/api/v1/resources/gizmos")
        assert code == 404


class TestExportImportReset:
    def test_export_import_roundtrip(self, server):
        p = server.port
        _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))
        _req(p, "PUT", "/api/v1/resources/pods", pod("w"))
        code, snap = _req(p, "GET", "/api/v1/export")
        assert code == 200
        assert {n["metadata"]["name"] for n in snap["nodes"]} == {"n0"}
        assert snap["schedulerConfig"]["profiles"]

        # import into a fresh server
        srv2 = SimulatorServer(SimulatorService(), port=0).start()
        try:
            code, out = _req(srv2.port, "POST", "/api/v1/import", snap)
            assert code == 200 and out["errors"] == []
            code, items = _req(srv2.port, "GET", "/api/v1/resources/pods")
            assert [i["metadata"]["name"] for i in items["items"]] == ["w"]
        finally:
            srv2.shutdown()

    def test_import_restarts_scheduler_with_snapshot_config(self, server):
        p = server.port
        code, snap = _req(p, "GET", "/api/v1/export")
        snap["schedulerConfig"] = {
            "profiles": [
                {
                    "schedulerName": "default-scheduler",
                    "plugins": {
                        "score": {
                            "disabled": [{"name": "*"}],
                            "enabled": [{"name": "ImageLocality", "weight": 3}],
                        }
                    },
                }
            ]
        }
        code, _ = _req(p, "POST", "/api/v1/import", snap)
        assert code == 200
        code, got = _req(p, "GET", "/api/v1/schedulerconfiguration")
        assert got["profiles"][0]["plugins"]["score"]["enabled"] == [
            {"name": "ImageLocality", "weight": 3}
        ]

    def test_reset_restores_boot_state_and_config(self, server):
        p = server.port
        _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))
        _req(
            p,
            "POST",
            "/api/v1/schedulerconfiguration",
            {
                "profiles": [
                    {
                        "schedulerName": "default-scheduler",
                        "plugins": {
                            "score": {
                                "disabled": [{"name": "*"}],
                                "enabled": [{"name": "ImageLocality"}],
                            }
                        },
                    }
                ]
            },
        )
        code, _ = _req(p, "PUT", "/api/v1/reset")
        assert code == 202
        code, items = _req(p, "GET", "/api/v1/resources/nodes")
        assert items["items"] == []
        code, cfg = _req(p, "GET", "/api/v1/schedulerconfiguration")
        # boot config restored: not the single-plugin score set posted above
        enabled = cfg["profiles"][0]["plugins"]["score"]["enabled"]
        assert enabled != [{"name": "ImageLocality"}]
        assert len(enabled) > 1


class TestListWatchStream:
    def test_list_as_added_then_live_events(self, server):
        p = server.port
        _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))

        events = []
        done = threading.Event()

        def consume():
            req = urllib.request.Request(
                f"http://127.0.0.1:{p}/api/v1/listwatchresources"
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                for line in resp:
                    if not line.strip():
                        continue  # heartbeat
                    events.append(json.loads(line))
                    if len(events) >= 2:
                        done.set()
                        return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        deadline = time.time() + 5
        while not events and time.time() < deadline:
            time.sleep(0.05)  # wait for the ADDED replay
        _req(p, "PUT", "/api/v1/resources/pods", pod("late"))
        assert done.wait(timeout=10)
        assert events[0]["Kind"] == "nodes"
        assert events[0]["EventType"] == "ADDED"
        live = events[1]
        assert live["Kind"] == "pods"
        assert live["Obj"]["metadata"]["name"] == "late"

    def test_last_resource_version_resumes(self, server):
        p = server.port
        _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))
        code, items = _req(p, "GET", "/api/v1/resources/nodes")
        rv = items["items"][0]["metadata"]["resourceVersion"]
        _req(p, "PUT", "/api/v1/resources/nodes", node("n1"))

        got = []

        def consume():
            req = urllib.request.Request(
                f"http://127.0.0.1:{p}/api/v1/listwatchresources"
                f"?nodesLastResourceVersion={rv}"
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                for line in resp:
                    if not line.strip():
                        continue
                    got.append(json.loads(line))
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        t.join(timeout=10)
        # only n1 (created after rv) is replayed
        assert got and got[0]["Obj"]["metadata"]["name"] == "n1"


class TestWatchParamValidation:
    def test_bad_last_resource_version_is_400(self, server):
        code, err = _req(
            server.port,
            "GET",
            "/api/v1/listwatchresources?podsLastResourceVersion=abc",
        )
        assert code == 400
        assert "podsLastResourceVersion" in err["message"]


class TestCORS:
    def test_allowed_origin_headers(self):
        srv = SimulatorServer(
            SimulatorService(),
            port=0,
            cors_allowed_origins=["http://localhost:3000"],
        ).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/api/v1/schedulerconfiguration",
                headers={"Origin": "http://localhost:3000"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert (
                    resp.headers["Access-Control-Allow-Origin"]
                    == "http://localhost:3000"
                )
            # disallowed origin gets no CORS header
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/api/v1/schedulerconfiguration",
                headers={"Origin": "http://evil.example"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.headers["Access-Control-Allow-Origin"] is None
        finally:
            srv.shutdown()


class TestCompileReuse:
    def test_second_pass_reuses_compiled_engine(self, server):
        p = server.port
        _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))
        _req(p, "PUT", "/api/v1/resources/pods", pod("a"))
        _req(p, "POST", "/api/v1/schedule")
        svc = server.service.scheduler

        def seq_engines():
            return [
                e for k, e in svc.broker._engines.items() if k[0] == "seq"
            ]

        assert len(seq_engines()) == 1
        first = seq_engines()[0]
        # same padded shapes: the cached engine must be retargeted, not
        # rebuilt (pow2 padding keeps shapes stable as the cluster grows)
        _req(p, "PUT", "/api/v1/resources/pods", pod("b"))
        _req(p, "POST", "/api/v1/schedule")
        assert seq_engines() == [first]
        assert svc.broker.compile_misses == 1
        assert svc.broker.compile_hits >= 1
        code, got = _req(p, "GET", "/api/v1/resources/pods/default/b")
        assert got["spec"]["nodeName"] == "n0"


class TestAutoSchedule:
    def test_pod_apply_triggers_pass(self):
        srv = SimulatorServer(SimulatorService(), port=0, auto_schedule=True)
        srv.start()
        try:
            p = srv.port
            _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))
            _req(p, "PUT", "/api/v1/resources/pods", pod("w"))
            code, got = _req(p, "GET", "/api/v1/resources/pods/default/w")
            assert got["spec"].get("nodeName") == "n0"
        finally:
            srv.shutdown()


class TestStoreHygiene:
    def test_reentrant_subscriber_no_deadlock(self):
        from kube_scheduler_simulator_tpu.models import ResourceStore

        store = ResourceStore()
        seen = []

        def reactor(ev):
            seen.append((ev.event_type, ev.kind, ev.resource_version))
            # re-entrant mutation from a subscriber must not deadlock
            if ev.kind == "nodes" and ev.event_type == "ADDED":
                store.apply(
                    "pods",
                    {"metadata": {"name": f"auto-{ev.obj['metadata']['name']}"}},
                )

        store.subscribe(reactor)
        store.apply("nodes", {"metadata": {"name": "n0"}})
        kinds = [k for _, k, _ in seen]
        assert kinds == ["nodes", "pods"]
        # delivery order matches log (resourceVersion) order
        rvs = [rv for _, _, rv in seen]
        assert rvs == sorted(rvs)

    def test_stale_resource_version_raises(self):
        from kube_scheduler_simulator_tpu.models import ResourceStore
        from kube_scheduler_simulator_tpu.models.store import StaleResourceVersion

        store = ResourceStore()
        store._events = []
        store._pruned_through = 10  # simulate a pruned log window
        with pytest.raises(StaleResourceVersion):
            store.events_since("pods", 5)

    def test_event_log_pruning(self):
        from kube_scheduler_simulator_tpu.models import ResourceStore
        from kube_scheduler_simulator_tpu.models.store import (
            StaleResourceVersion,
            WatchEvent,
        )

        store = ResourceStore()
        with store._lock:
            for i in range(100_001):
                store._emit(WatchEvent("ADDED", "pods", {}, i + 1))
            store._delivery.clear()
        assert store._pruned_through == 50_000
        with pytest.raises(StaleResourceVersion):
            store.events_since("pods", 10_000)
        # events after the pruned window still replay
        assert store.events_since("pods", 100_000)[0].resource_version == 100_001
