"""Serving-shell controller convergence: the reference's controller
subset runs continuously against its apiserver (POST a Deployment, GET
its Pods — simulator/controller/controller.go:31-46); here every
mutation through the HTTP surface runs the deterministic step functions
to a fixpoint."""

import json
import urllib.request

from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import SimulatorService

from helpers import node


def _req(url, data=None, method="GET"):
    req = urllib.request.Request(
        url,
        data=None if data is None else json.dumps(data).encode(),
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        body = resp.read()
        return resp.status, json.loads(body) if body else None


def deployment(name, replicas):
    labels = {"app": name}
    return {
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "containers": [
                        {"name": "c", "resources": {"requests": {"cpu": "100m"}}}
                    ]
                },
            },
        },
    }


class TestServingControllers:
    def setup_method(self):
        self.server = SimulatorServer(
            SimulatorService(), port=0, auto_schedule=True
        ).start()
        self.base = f"http://127.0.0.1:{self.server.port}/api/v1"

    def teardown_method(self):
        self.server.shutdown()

    def test_deployment_expands_and_schedules(self):
        _req(f"{self.base}/resources/nodes", data=node("n0"), method="POST")
        st, _ = _req(
            f"{self.base}/resources/deployments",
            data=deployment("web", 3),
            method="POST",
        )
        assert st == 201
        # replicasets + pods exist without any scenario run
        _, rs = _req(f"{self.base}/resources/replicasets")
        assert len(rs["items"]) == 1
        _, pods = _req(f"{self.base}/resources/pods")
        assert len(pods["items"]) == 3
        # ... and auto_schedule bound them
        assert all(p["spec"].get("nodeName") == "n0" for p in pods["items"])

    def test_scale_down_via_put(self):
        _req(f"{self.base}/resources/nodes", data=node("n0"), method="POST")
        _req(
            f"{self.base}/resources/deployments",
            data=deployment("web", 3),
            method="POST",
        )
        d = deployment("web", 1)
        st, _ = _req(
            f"{self.base}/resources/deployments/default/web",
            data=d,
            method="PUT",
        )
        assert st == 200
        _, pods = _req(f"{self.base}/resources/pods")
        assert len(pods["items"]) == 1

    def test_delete_deployment_cascades(self):
        _req(f"{self.base}/resources/nodes", data=node("n0"), method="POST")
        _req(
            f"{self.base}/resources/deployments",
            data=deployment("web", 3),
            method="POST",
        )
        st, _ = _req(
            f"{self.base}/resources/deployments/default/web", method="DELETE"
        )
        assert st == 200
        _, rs = _req(f"{self.base}/resources/replicasets")
        assert rs["items"] == []
        _, pods = _req(f"{self.base}/resources/pods")
        assert pods["items"] == []

    def test_malformed_replicas_does_not_wedge_crud(self):
        bad = deployment("bad", 3)
        bad["spec"]["replicas"] = "three"
        st, _ = _req(
            f"{self.base}/resources/deployments", data=bad, method="POST"
        )
        assert st == 201  # stored; the malformed spec is skipped, not fatal
        # the CRUD surface still works for everything else
        st, _ = _req(f"{self.base}/resources/nodes", data=node("n0"), method="POST")
        assert st == 201
        _, pods = _req(f"{self.base}/resources/pods")
        assert pods["items"] == []  # nothing expanded from the bad spec

    def test_export_import_roundtrip_keeps_workloads(self):
        """Snapshot round-trip: the workload kinds ride as extension keys
        and RS-owned pods survive import (no ambient owner GC)."""
        _req(f"{self.base}/resources/nodes", data=node("n0"), method="POST")
        _req(
            f"{self.base}/resources/deployments",
            data=deployment("web", 2),
            method="POST",
        )
        _, snap = _req(f"{self.base}/export")
        assert len(snap["deployments"]) == 1
        assert len(snap["replicasets"]) == 1
        assert len(snap["pods"]) == 2
        # wipe and re-import
        urllib.request.urlopen(
            urllib.request.Request(
                f"{self.base}/reset", data=b"", method="PUT"
            )
        )
        st, out = _req(f"{self.base}/import", data=snap, method="POST")
        assert st == 200 and out["errors"] == []
        _, pods = _req(f"{self.base}/resources/pods")
        assert len(pods["items"]) == 2  # survived: no GC on import
        _, deps = _req(f"{self.base}/resources/deployments")
        assert len(deps["items"]) == 1
        # the workload is still scalable after the round-trip
        st, _ = _req(
            f"{self.base}/resources/deployments/default/web",
            data=deployment("web", 4),
            method="PUT",
        )
        assert st == 200
        _, pods = _req(f"{self.base}/resources/pods")
        assert len(pods["items"]) == 4

    def test_pv_binding_on_crud(self):
        pvc = {
            "metadata": {"name": "claim", "namespace": "default"},
            "spec": {
                "storageClassName": "",
                "resources": {"requests": {"storage": "1Gi"}},
            },
        }
        pv = {
            "metadata": {"name": "vol"},
            "spec": {"storageClassName": "", "capacity": {"storage": "1Gi"}},
        }
        _req(f"{self.base}/resources/pvcs", data=pvc, method="POST")
        _req(f"{self.base}/resources/pvs", data=pv, method="POST")
        _, got = _req(f"{self.base}/resources/pvs/vol")
        assert (got["spec"].get("claimRef") or {}).get("name") == "claim"
