"""Service-level gang scheduling: write-back, cache reuse, HTTP route."""

import json
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.server.service import SimulatorService

from helpers import node, pod


def _fill(svc, n_nodes=3, n_pods=6):
    for i in range(n_nodes):
        svc.store.apply("nodes", node(f"n{i}"))
    for i in range(n_pods):
        svc.store.apply("pods", pod(f"p{i}"))


def test_gang_pass_writes_node_names_and_annotations():
    svc = SimulatorService()
    _fill(svc)
    placements, rounds, results = svc.scheduler.schedule_gang()
    assert rounds >= 1
    assert all(v for v in placements.values())
    assert results and len(
        {(r.pod_namespace, r.pod_name) for r in results}
    ) == 6
    for i in range(6):
        obj = svc.store.get("pods", f"p{i}", "default")
        assert obj["spec"]["nodeName"] == placements[("default", f"p{i}")]
        ann = obj["metadata"]["annotations"]
        # the 13-annotation product, now on gang runs too (VERDICT r4 #6)
        assert (
            ann["scheduler-simulator/selected-node"]
            == placements[("default", f"p{i}")]
        )
        assert "scheduler-simulator/score-result" in ann
        assert "scheduler-simulator/filter-result" in ann


def test_gang_pass_record_off_writes_node_names_only():
    svc = SimulatorService()
    _fill(svc)
    placements, rounds, results = svc.scheduler.schedule_gang(record=False)
    assert results is None and rounds >= 1
    for i in range(6):
        obj = svc.store.get("pods", f"p{i}", "default")
        assert obj["spec"]["nodeName"] == placements[("default", f"p{i}")]
        assert not (obj["metadata"].get("annotations") or {})


def test_gang_pass_deletes_preemption_victims():
    """The gang preempt phase evicts pre-bound victims; the write-back
    must delete them from the store exactly like the sequential path
    (upstream preemption deletes victims through the API) — otherwise
    the next pass encodes a double-booked node."""
    svc = SimulatorService()
    for i in range(2):
        svc.store.apply("nodes", node(f"n{i}", cpu="2", pods="8"))
        svc.store.apply(
            "pods",
            pod(f"low-{i}", cpu="1800m", priority=1, node_name=f"n{i}"),
        )
    for i in range(2):
        svc.store.apply("pods", pod(f"high-{i}", cpu="1500m", priority=100))
    placements, _, _ = svc.scheduler.schedule_gang()
    assert placements[("default", "high-0")] != ""
    assert placements[("default", "high-1")] != ""
    # the victims are gone from the store
    for i in range(2):
        assert svc.store.get("pods", f"low-{i}", "default") is None
    # and a follow-up pass over the SAME store doesn't see phantom load:
    # both nodes hold exactly one (high) pod
    for i in range(2):
        assert len(svc.store.pods_on_node(f"n{i}")) == 1


def test_gang_engine_cache_reused_across_passes():
    svc = SimulatorService()
    _fill(svc)
    svc.scheduler.schedule_gang()

    def gang_engines():
        return [
            e
            for k, e in svc.scheduler.broker._engines.items()
            if k[0] == "gang"
        ]

    assert len(gang_engines()) == 1
    gang0 = gang_engines()[0]
    # same shapes/config: second pass must reuse the compiled engine
    svc.store.apply("pods", pod("extra"))
    svc.scheduler.schedule_gang()
    assert gang_engines() == [gang0]
    assert svc.store.get("pods", "extra", "default")["spec"].get("nodeName")


def test_gang_rejects_extenders():
    svc = SimulatorService()
    _fill(svc)
    svc.scheduler._config.extenders.append(
        {"urlPrefix": "http://localhost:9", "filterVerb": "filter"}
    )
    with pytest.raises(ValueError, match="extenders"):
        svc.scheduler.schedule_gang()


def test_http_gang_route():
    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer

    svc = SimulatorService()
    _fill(svc, n_nodes=2, n_pods=4)
    server = SimulatorServer(svc, port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}/api/v1"
        with urllib.request.urlopen(
            urllib.request.Request(
                f"{base}/schedule?mode=gang", data=b"", method="POST"
            )
        ) as resp:
            out = json.load(resp)
        assert out["mode"] == "gang"
        assert out["scheduled"] == 4
        assert out["rounds"] >= 1
        # records default ON: the response carries per-pod results and
        # the store's pods carry the 13 annotations (webui inspect path)
        assert len(out["results"]) == 4
        assert all(r["status"] == "Scheduled" for r in out["results"])
        obj = svc.store.get("pods", "p0", "default")
        assert (
            "scheduler-simulator/selected-node"
            in obj["metadata"]["annotations"]
        )
        # and ?record=0 opts out
        with urllib.request.urlopen(
            urllib.request.Request(
                f"{base}/schedule?mode=gang&record=0", data=b"", method="POST"
            )
        ) as resp:
            out2 = json.load(resp)
        assert "results" not in out2
    finally:
        server.shutdown()


def test_gang_window_through_service_and_http():
    """?window=W passes eval_window through to the gang program (the
    at-scale round-cost lever): placements complete, records intact,
    the engine cache keys on the window (a windowed program is a
    different compile), and a malformed window is a 400."""
    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer

    svc = SimulatorService()
    _fill(svc, n_nodes=2, n_pods=8)
    placements, rounds, results = svc.scheduler.schedule_gang(window=2)
    assert all(v for v in placements.values())
    assert results and len(results) >= 8
    def cached_windows():
        return [
            k[2]
            for k in svc.scheduler.broker._engines
            if k[0] == "gang"
        ]

    # window=2 on 8 pods with the default chunk never binds (WP rounds
    # past P) — the canonical key is None, shared with unwindowed
    assert cached_windows() == [None]
    # a BINDING window is its own cached program, and the unwindowed
    # one survives beside it (alternating clients don't recompile)
    for i in range(8, 12):
        svc.store.apply("pods", pod(f"p{i}"))
    svc.scheduler.schedule_gang()
    before = len(cached_windows())
    # P grew; the fresh encoding has its own signature — find a window
    # that binds: the serving chunk (service.GANG_CHUNK, 64) is >= P
    # here so none can; assert the canonicalization instead: distinct
    # raw windows share the key
    svc.scheduler.schedule_gang(window=3)
    svc.scheduler.schedule_gang(window=7)
    assert len(cached_windows()) == before
    with pytest.raises(ValueError, match="window"):
        svc.scheduler.schedule_gang(window=0)

    for i in range(12, 14):
        svc.store.apply("pods", pod(f"p{i}"))
    server = SimulatorServer(svc, port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}/api/v1"
        with urllib.request.urlopen(
            urllib.request.Request(
                f"{base}/schedule?mode=gang&window=2", data=b"",
                method="POST",
            )
        ) as resp:
            out = json.load(resp)
        assert out["mode"] == "gang" and out["scheduled"] == 2
        try:
            urllib.request.urlopen(
                urllib.request.Request(
                    f"{base}/schedule?mode=gang&window=abc", data=b"",
                    method="POST",
                )
            )
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.shutdown()
