"""The multi-tenant session plane (docs/sessions.md): session CRUD and
isolation, the shared CompileBroker (one build across bucket-compatible
tenants, per-session bulkheads for fault storms), admission control's
structured 503s, evict/restore round-trips, readiness, SSE hardening,
the Prometheus `session` label, and strict KSS_* env validation."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService
from kube_scheduler_simulator_tpu.server.sessions import SessionManager
from kube_scheduler_simulator_tpu.utils import envcheck, telemetry
from kube_scheduler_simulator_tpu.utils.metrics import parse_prometheus_text

from helpers import node, pod


def _req(port, method, path, body=None, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None, dict(resp.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None, dict(e.headers)


def _server(**session_config):
    return SimulatorServer(
        SimulatorService(), port=0, session_config=session_config
    ).start()


@pytest.fixture()
def server():
    srv = _server()
    yield srv
    srv.shutdown()


def _mksession(port, body=None):
    code, doc, _ = _req(port, "POST", "/api/v1/sessions", body or {})
    assert code == 201, doc
    return doc["id"]


class TestSessionCrudAndIsolation:
    def test_create_list_get_delete(self, server):
        p = server.port
        sid = _mksession(p, {"name": "tenant-a"})
        code, lst, _ = _req(p, "GET", "/api/v1/sessions")
        assert code == 200
        assert {s["id"] for s in lst["sessions"]} == {"default", sid}
        assert "compileMisses" in lst["broker"]
        code, info, _ = _req(p, "GET", f"/api/v1/sessions/{sid}")
        assert code == 200 and info["name"] == "tenant-a"
        code, _, _ = _req(p, "DELETE", f"/api/v1/sessions/{sid}")
        assert code == 200
        code, err, _ = _req(p, "GET", f"/api/v1/sessions/{sid}")
        assert code == 404 and err["kind"] == "UnknownSession"

    def test_sessions_are_isolated_from_each_other_and_default(self, server):
        p = server.port
        a = _mksession(p)
        b = _mksession(p)
        _req(p, "PUT", f"/api/v1/sessions/{a}/resources/nodes", node("n0"))
        _req(p, "PUT", f"/api/v1/sessions/{a}/resources/pods", pod("w"))
        for path in (
            f"/api/v1/sessions/{b}/resources/pods",
            "/api/v1/resources/pods",  # legacy = default session
        ):
            code, items, _ = _req(p, "GET", path)
            assert code == 200 and items["items"] == []
        # scheduling in A binds A's pod and nobody else's metrics move
        code, out, _ = _req(p, "POST", f"/api/v1/sessions/{a}/schedule")
        assert code == 200 and out["scheduled"] == 1
        code, mb, _ = _req(p, "GET", f"/api/v1/sessions/{b}/metrics")
        assert mb["passes"] == 0

    def test_default_session_cannot_be_deleted_or_evicted(self, server):
        p = server.port
        assert _req(p, "DELETE", "/api/v1/sessions/default")[0] == 400
        assert _req(p, "POST", "/api/v1/sessions/default/evict")[0] == 400

    def test_bad_fault_spec_is_400(self, server):
        code, err, _ = _req(
            server.port,
            "POST",
            "/api/v1/sessions",
            {"faultInject": "no_such_site:1.0"},
        )
        assert code == 400
        assert "no_such_site" in err["error"]

    def test_create_with_snapshot_imports(self, server):
        p = server.port
        snap = {"nodes": [node("sn0")], "pods": [pod("sp0")]}
        code, doc, _ = _req(p, "POST", "/api/v1/sessions", {"snapshot": snap})
        assert code == 201 and doc["errors"] == []
        code, items, _ = _req(
            p, "GET", f"/api/v1/sessions/{doc['id']}/resources/nodes"
        )
        assert [i["metadata"]["name"] for i in items["items"]] == ["sn0"]


class TestFork:
    def test_fork_branches_state(self, server):
        p = server.port
        a = _mksession(p)
        _req(p, "PUT", f"/api/v1/sessions/{a}/resources/nodes", node("n0"))
        _req(p, "PUT", f"/api/v1/sessions/{a}/resources/pods", pod("w"))
        code, fk, _ = _req(p, "POST", f"/api/v1/sessions/{a}/fork")
        assert code == 201
        b = fk["id"]
        code, items, _ = _req(p, "GET", f"/api/v1/sessions/{b}/resources/pods")
        assert [i["metadata"]["name"] for i in items["items"]] == ["w"]
        # divergence: deleting in the fork leaves the source untouched
        _req(p, "DELETE", f"/api/v1/sessions/{b}/resources/pods/default/w")
        code, items, _ = _req(p, "GET", f"/api/v1/sessions/{a}/resources/pods")
        assert [i["metadata"]["name"] for i in items["items"]] == ["w"]


class TestSharedBroker:
    def test_bucket_compatible_sessions_share_one_build(self, server):
        """The tentpole's sharing contract + the thread-safety
        satellite: two sessions with bucket-compatible clusters
        scheduling CONCURRENTLY produce exactly one compile — the
        shared broker's warm map + per-key lease serve the second
        tenant without a second build."""
        p = server.port
        sids = [_mksession(p) for _ in range(2)]
        for sid in sids:
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/nodes", node("n0"))
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/pods", pod("w"))
        results = {}

        def run(sid):
            results[sid] = _req(p, "POST", f"/api/v1/sessions/{sid}/schedule")

        threads = [threading.Thread(target=run, args=(s,)) for s in sids]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        for sid in sids:
            code, out, _ = results[sid]
            assert code == 200 and out["scheduled"] == 1
        assert server.sessions.broker.compile_misses == 1
        assert server.sessions.broker.compile_hits >= 1


class TestBulkheadIsolation:
    def test_fault_storm_confined_to_one_session(self, server, monkeypatch):
        """The acceptance criterion: a compile_fail:1.0 storm scoped to
        session A (the KSS_FAULT_INJECT grammar, session-scoped) leaves
        A completing every pass on the eager rung while B's passes stay
        jitted — B's eagerFallbacks/degradedPasses never move and its
        warm passes keep hitting the shared broker."""
        monkeypatch.setenv("KSS_COMPILE_BACKOFF_S", "0.001")
        p = server.port
        # A storms in gang mode, B stays sequential: distinct broker
        # keys, so A's never-compiling key can't be served warm by B
        a = _mksession(p, {"faultInject": "compile_fail:1.0"})
        b = _mksession(p)
        for sid in (a, b):
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/nodes", node("n0"))
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/pods", pod("w"))
        # B warms up first: one cold compile, then pure warm hits
        code, out, _ = _req(p, "POST", f"/api/v1/sessions/{b}/schedule")
        assert code == 200 and out["scheduled"] == 1
        # A's storm: every pass completes anyway (the eager rung)
        for i in range(2):
            _req(
                p, "PUT", f"/api/v1/sessions/{a}/resources/pods", pod(f"x{i}")
            )
            code, out, _ = _req(
                p, "POST", f"/api/v1/sessions/{a}/schedule?mode=gang&record=0"
            )
            assert code == 200, out
            assert out["scheduled"] >= 1
        # B keeps serving warm, jitted passes mid-storm
        _req(p, "PUT", f"/api/v1/sessions/{b}/resources/pods", pod("y"))
        code, out, _ = _req(p, "POST", f"/api/v1/sessions/{b}/schedule")
        assert code == 200 and out["scheduled"] == 1
        code, ma, _ = _req(p, "GET", f"/api/v1/sessions/{a}/metrics")
        code, mb, _ = _req(p, "GET", f"/api/v1/sessions/{b}/metrics")
        assert ma["phases"]["eagerFallbacks"] >= 2
        assert ma["phases"]["degradedPasses"] >= 2
        assert ma["phases"]["compileMisses"] == 0  # nothing ever compiled
        # the bulkhead: the healthy neighbor never degraded
        assert mb["phases"]["eagerFallbacks"] == 0
        assert mb["phases"]["degradedPasses"] == 0
        assert mb["phases"]["compileMisses"] == 1  # its own cold start only
        assert mb["phases"]["compileHits"] >= 1  # warm mid-storm


class TestAdmissionControl:
    def test_session_limit_sheds_with_structured_503(self):
        srv = _server(max_sessions=2)  # default + 1
        try:
            p = srv.port
            _mksession(p)
            code, err, headers = _req(p, "POST", "/api/v1/sessions", {})
            assert code == 503
            assert err["kind"] == "SessionLimitExceeded"
            assert "error" in err and "detail" in err
            assert headers.get("Retry-After")
        finally:
            srv.shutdown()

    def test_pending_pod_quota(self):
        srv = _server(pending_pod_quota=2)
        try:
            p = srv.port
            sid = _mksession(p)
            base = f"/api/v1/sessions/{sid}/resources/pods"
            assert _req(p, "PUT", base, pod("a"))[0] == 201
            assert _req(p, "PUT", base, pod("b"))[0] == 201
            code, err, headers = _req(p, "PUT", base, pod("c"))
            assert code == 503
            assert err["kind"] == "SessionQuotaExceeded"
            assert headers.get("Retry-After")
            # bound pods don't consume pending quota
            assert _req(p, "PUT", base, pod("d", node_name="n0"))[0] == 201
        finally:
            srv.shutdown()

    def test_quota_allows_updates_to_existing_pending_pods(self):
        """Admission meters queue GROWTH, not pod shape: a tenant at
        quota must still be able to label or correct pods already in its
        queue — the count doesn't change."""
        srv = _server(pending_pod_quota=2)
        try:
            p = srv.port
            sid = _mksession(p)
            base = f"/api/v1/sessions/{sid}/resources/pods"
            assert _req(p, "PUT", base, pod("a"))[0] == 201
            assert _req(p, "PUT", base, pod("b"))[0] == 201
            relabel = pod("a")
            relabel["metadata"]["labels"] = {"tier": "gold"}
            code, obj, _ = _req(p, "POST", base, relabel)  # collection apply
            assert code == 201
            assert obj["metadata"]["labels"]["tier"] == "gold"
            code, _, _ = _req(p, "PUT", base + "/default/a", relabel)  # replace
            assert code == 200
        finally:
            srv.shutdown()

    def test_quota_meters_unbind_via_replace(self):
        """The bypass: bound pods are admitted freely, but an item PUT
        whose body omits spec.nodeName UNBINDS the pod back into the
        pending queue (replace deletes absent fields) — without metering
        that transition a tenant could turn N bound pods into an
        arbitrarily long queue past KSS_MAX_PENDING_PODS_PER_SESSION."""
        srv = _server(pending_pod_quota=1)
        try:
            p = srv.port
            sid = _mksession(p)
            base = f"/api/v1/sessions/{sid}/resources/pods"
            for name in ("a", "b"):
                assert _req(p, "PUT", base, pod(name, node_name="n0"))[0] == 201
            # the first unbind fills the quota...
            assert _req(p, "PUT", base + "/default/a", pod("a"))[0] == 200
            # ...the second would exceed it and is shed
            code, err, _ = _req(p, "PUT", base + "/default/b", pod("b"))
            assert code == 503 and err["kind"] == "SessionQuotaExceeded"
        finally:
            srv.shutdown()

    def test_concurrent_pass_semaphore_sheds(self):
        srv = _server(max_concurrent_passes=1)
        try:
            p = srv.port
            sid = _mksession(p)
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/nodes", node("n0"))
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/pods", pod("w"))
            assert srv.sessions._pass_sem.acquire(blocking=False)
            try:
                code, err, headers = _req(
                    p, "POST", f"/api/v1/sessions/{sid}/schedule"
                )
                assert code == 503
                assert err["kind"] == "ServerSaturated"
                assert headers.get("Retry-After")
            finally:
                srv.sessions._pass_sem.release()
            code, out, _ = _req(p, "POST", f"/api/v1/sessions/{sid}/schedule")
            assert code == 200 and out["scheduled"] == 1
        finally:
            srv.shutdown()


class TestSlotStarvation:
    def test_same_session_schedule_sheds_instead_of_queueing(self, server):
        """A session with a pass already in flight sheds further
        /schedule requests BEFORE they claim a concurrent-pass slot:
        queued same-session waiters would otherwise hold the global
        slots doing no device work, starving every other tenant."""
        p = server.port
        sid = _mksession(p)
        _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/nodes", node("n0"))
        _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/pods", pod("w"))
        svc = server.sessions.get(sid).service
        assert svc.scheduler._schedule_lock.acquire(blocking=False)
        try:
            code, err, headers = _req(
                p, "POST", f"/api/v1/sessions/{sid}/schedule"
            )
            assert code == 503
            assert err["kind"] == "ServerSaturated"
            assert "pass in flight" in err["error"]
            assert headers.get("Retry-After")
            # no slot was consumed by the shed request
            assert server.sessions._pass_sem.acquire(blocking=False)
            server.sessions._pass_sem.release()
        finally:
            svc.scheduler._schedule_lock.release()
        code, out, _ = _req(p, "POST", f"/api/v1/sessions/{sid}/schedule")
        assert code == 200 and out["scheduled"] == 1


class TestEvictRestore:
    def test_evict_then_touch_restores_without_loss(self, server):
        p = server.port
        sid = _mksession(p)
        _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/nodes", node("n0"))
        _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/pods", pod("w"))
        code, out, _ = _req(p, "POST", f"/api/v1/sessions/{sid}/schedule")
        assert code == 200 and out["scheduled"] == 1
        code, before, _ = _req(
            p, "GET", f"/api/v1/sessions/{sid}/resources/pods"
        )
        code, ev, _ = _req(p, "POST", f"/api/v1/sessions/{sid}/evict")
        assert code == 200 and ev["snapshot"]
        code, info, _ = _req(p, "GET", f"/api/v1/sessions/{sid}")
        assert info["state"] == "evicted"
        # transparent restore on the next touch: objects verbatim
        # (resourceVersions included) and cumulative metrics intact
        code, after, _ = _req(
            p, "GET", f"/api/v1/sessions/{sid}/resources/pods"
        )
        assert code == 200 and after == before
        code, m, _ = _req(p, "GET", f"/api/v1/sessions/{sid}/metrics")
        assert m["passes"] == 1
        code, info, _ = _req(p, "GET", f"/api/v1/sessions/{sid}")
        assert info["state"] == "live" and info["restores"] == 1

    def test_evict_refused_while_request_in_flight(self, server):
        """Eviction excludes in-flight REQUESTS, not just passes: a CRUD
        the server is about to acknowledge must not be applied to a
        service object eviction is discarding (data loss). `using` is
        the HTTP layer's per-request registration."""
        from kube_scheduler_simulator_tpu.server.sessions import SessionBusy

        p = server.port
        sid = _mksession(p)
        mgr = server.sessions
        with mgr.using(sid):
            with pytest.raises(SessionBusy):
                mgr.evict(sid)
        assert mgr.evict(sid)  # quiesced: eviction proceeds
        assert _req(p, "GET", f"/api/v1/sessions/{sid}")[1]["state"] == "evicted"

    def test_idle_sweeper_evicts_and_touch_revives(self):
        srv = _server(idle_evict_s=0.25)
        try:
            p = srv.port
            sid = _mksession(p)
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/nodes", node("n0"))
            deadline = time.time() + 10
            state = "live"
            while state != "evicted" and time.time() < deadline:
                time.sleep(0.1)
                state = _req(p, "GET", f"/api/v1/sessions/{sid}")[1]["state"]
            assert state == "evicted"
            code, items, _ = _req(
                p, "GET", f"/api/v1/sessions/{sid}/resources/nodes"
            )
            assert code == 200
            assert [i["metadata"]["name"] for i in items["items"]] == ["n0"]
        finally:
            srv.shutdown()


class TestReadiness:
    def test_readyz_degrades_on_cooldown_and_worker_crash(self, server):
        p = server.port
        assert _req(p, "GET", "/api/v1/healthz")[0] == 200
        assert _req(p, "GET", "/api/v1/readyz")[0] == 200
        broker = server.sessions.broker
        broker._cooldown[("sess", ("k",))] = (3, time.monotonic())
        try:
            code, doc, headers = _req(p, "GET", "/api/v1/readyz")
            assert code == 503 and not doc["ready"]
            assert headers.get("Retry-After")
            assert any("cooldown" in r for r in doc["reasons"])
        finally:
            broker._cooldown.clear()
        assert _req(p, "GET", "/api/v1/readyz")[0] == 200
        broker.worker_crashes = 1
        try:
            code, doc, _ = _req(p, "GET", "/api/v1/readyz")
            assert code == 503
            assert any("worker" in r for r in doc["reasons"])
        finally:
            broker.worker_crashes = 0


class TestSSEHardening:
    def test_subscriber_cap_sheds(self):
        srv = _server(sse_max_subscribers=1)
        try:
            p = srv.port
            first = urllib.request.urlopen(
                f"http://127.0.0.1:{p}/api/v1/events", timeout=10
            )
            try:
                code, err, headers = _req(p, "GET", "/api/v1/events", timeout=10)
                assert code == 503
                assert err["kind"] == "SSESubscriberLimit"
                assert headers.get("Retry-After")
            finally:
                first.close()
        finally:
            srv.shutdown()

    def test_slow_consumer_disconnected_and_drops_counted(
        self, server, monkeypatch
    ):
        from kube_scheduler_simulator_tpu.server import httpserver

        monkeypatch.setattr(httpserver, "SSE_QUEUE_MAX", 4)
        rec = telemetry.SpanRecorder(capacity=4096)
        telemetry.activate(rec)
        try:
            p = server.port
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{p}/api/v1/events", timeout=10
            )
            try:
                deadline = time.time() + 5
                while server._sse_subs < 1 and time.time() < deadline:
                    time.sleep(0.02)
                # a stalled client: never reads while spans flood in
                for i in range(64):
                    telemetry.instant("flood", i=i)
                deadline = time.time() + 10
                while server.sse_dropped == 0 and time.time() < deadline:
                    time.sleep(0.05)
                assert server.sse_dropped >= 1
                # the slot is reclaimed: the server disconnected us
                deadline = time.time() + 10
                while server._sse_subs > 0 and time.time() < deadline:
                    time.sleep(0.05)
                assert server._sse_subs == 0
            finally:
                resp.close()
            code, doc, _ = _req(p, "GET", "/api/v1/metrics")
            assert doc["sseDroppedEvents"] >= 1
        finally:
            telemetry.deactivate()


class TestTelemetrySessionLabels:
    def test_spans_carry_session_id(self, server):
        rec = telemetry.SpanRecorder(capacity=4096)
        telemetry.activate(rec)
        try:
            p = server.port
            sid = _mksession(p)
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/nodes", node("n0"))
            _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/pods", pod("w"))
            code, out, _ = _req(p, "POST", f"/api/v1/sessions/{sid}/schedule")
            assert code == 200
            sessions = {
                (ev.get("args") or {}).get("session")
                for ev in rec.snapshot()
                if ev["name"].startswith("pass.")
            }
            assert sid in sessions
        finally:
            telemetry.deactivate()

    def test_prometheus_exposition_labels_every_session(self, server):
        p = server.port
        sid = _mksession(p)
        code, _, _ = _req(p, "GET", "/api/v1/metrics")
        req = urllib.request.Request(
            f"http://127.0.0.1:{p}/api/v1/metrics?format=prometheus"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            families = parse_prometheus_text(resp.read().decode())
        labels = {
            lab.get("session")
            for _, lab, _ in families["kss_passes_total"]["samples"]
        }
        assert labels == {"default", sid}
        # histograms validate per label set (the parser groups by series)
        assert families["kss_pass_latency_seconds"]["type"] == "histogram"
        # the nested per-session scrape carries just that session
        req = urllib.request.Request(
            f"http://127.0.0.1:{p}/api/v1/sessions/{sid}/metrics"
            f"?format=prometheus"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            families = parse_prometheus_text(resp.read().decode())
        labels = {
            lab.get("session")
            for _, lab, _ in families["kss_passes_total"]["samples"]
        }
        assert labels == {sid}


class TestSessionManagerUnit:
    def test_manager_env_parsing_is_strict(self):
        with pytest.raises(ValueError, match="KSS_MAX_SESSIONS"):
            SessionManager(
                SimulatorService(), env={"KSS_MAX_SESSIONS": "lots"}
            )
        with pytest.raises(ValueError, match="must be >= 1"):
            SessionManager(
                SimulatorService(), env={"KSS_MAX_CONCURRENT_PASSES": "0"}
            )


class TestEnvCheck:
    def test_clean_env_passes(self):
        assert envcheck.check_env({}) == []
        assert envcheck.check_env(
            {"KSS_ENCODING_CACHE_CAP": "16", "KSS_FAULT_INJECT": "compile_fail:0.5"}
        ) == []

    def test_malformed_values_are_reported(self):
        problems = envcheck.check_env(
            {
                "KSS_ENCODING_CACHE_CAP": "abc",
                "KSS_COMPILE_DEADLINE_S": "-1",
                "KSS_FAULT_INJECT": "bogus_site:1.0",
            }
        )
        text = "\n".join(problems)
        assert "KSS_ENCODING_CACHE_CAP" in text
        assert "KSS_COMPILE_DEADLINE_S" in text
        assert "bogus_site" in text

    def test_unknown_kss_variable_is_a_typo(self):
        problems = envcheck.check_env({"KSS_ENCODNG_CACHE_CAP": "8"})
        assert problems and "unknown" in problems[0]

    def test_fail_fast_exits_2(self, capsys):
        with pytest.raises(SystemExit) as exc:
            envcheck.fail_fast({"KSS_TRACE_RING_CAP": "huge"})
        assert exc.value.code == 2
        assert "KSS_TRACE_RING_CAP" in capsys.readouterr().err
        envcheck.fail_fast({})  # clean env: no exit

    def test_boolean_vocabulary_matches_runtime_parsers(self, monkeypatch):
        """Every boolean spelling check_env blesses must flip the
        runtime switches: a value validation accepts but the runtime
        silently ignores (KSS_NO_SPECULATIVE_COMPILE=on leaving
        speculation enabled, KSS_TRACE=t recording nothing) is exactly
        the misconfiguration class envcheck exists to kill."""
        from kube_scheduler_simulator_tpu.utils import broker as broker_mod

        for raw in envcheck.TRUTHY:
            assert envcheck.check_env({"KSS_NO_SPECULATIVE_COMPILE": raw}) == []
            monkeypatch.setenv("KSS_NO_SPECULATIVE_COMPILE", raw)
            assert broker_mod.speculation_enabled_default() is False, raw
            monkeypatch.setenv("KSS_TRACE", raw)
            assert telemetry.active() is not None, raw
        for raw in envcheck.FALSY:
            assert envcheck.check_env({"KSS_NO_SPECULATIVE_COMPILE": raw}) == []
            monkeypatch.setenv("KSS_NO_SPECULATIVE_COMPILE", raw)
            assert broker_mod.speculation_enabled_default() is True, raw
            monkeypatch.setenv("KSS_TRACE", raw)
            assert telemetry.active() is None, raw


class TestSharedBrokerHygiene:
    """The review-hardening set: a dead or chaos-testing tenant must not
    leave the SHARED broker (and with it /api/v1/readyz) degraded."""

    def test_speculative_build_attributes_to_arming_metrics(self):
        """On a shared broker, a speculative build counts into the
        ARMING service's registry (the session that requested it) — not
        nowhere (metrics=None froze speculativeCompiles at 0)."""
        from kube_scheduler_simulator_tpu.utils.broker import CompileBroker
        from kube_scheduler_simulator_tpu.utils.metrics import SchedulingMetrics

        broker = CompileBroker(speculative=True)
        m = SchedulingMetrics()
        assert broker.speculate(
            "t", lambda: (("k",), lambda: "engine"), metrics=m
        )
        assert broker.drain(timeout=10)
        assert m.snapshot()["phases"]["speculativeCompiles"] == 1
        assert broker.peek(("k",)) == "engine"

    def test_lease_map_bounded_by_warm_map(self):
        """The per-key lease dict retires entries with their engine's
        LRU eviction instead of growing with lifetime shape diversity."""
        from kube_scheduler_simulator_tpu.utils.broker import CompileBroker

        broker = CompileBroker()
        broker.capacity = 2
        for i in range(6):
            key = ("k", i)
            broker.lease(key)
            broker.get(key, lambda i=i: f"engine{i}")
        assert len(broker._engines) == 2
        assert set(broker._leases) == set(broker._engines)

    def test_stale_cooldown_expires_from_readyz(self, server, monkeypatch):
        """Cooldowns drain per pass OF THEIR OWN SCOPE, so a tenant that
        simply stops sending traffic (idle, evicted — delete is not the
        only way to go quiet) would pin readyz at 503 forever. Untouched
        entries expire after KSS_COMPILE_COOLDOWN_TTL_S and health()
        prunes them."""
        monkeypatch.setenv("KSS_COMPILE_COOLDOWN_TTL_S", "0.05")
        p = server.port
        broker = server.sessions.broker
        broker._cooldown[("gone-quiet", ("k",))] = (3, time.monotonic())
        assert _req(p, "GET", "/api/v1/readyz")[0] == 503
        time.sleep(0.1)
        assert _req(p, "GET", "/api/v1/readyz")[0] == 200
        assert broker._cooldown == {}

    def test_delete_purges_scope_cooldowns_readyz_recovers(self, server):
        p = server.port
        sid = _mksession(p)
        broker = server.sessions.broker
        # the tenant's compile ladder exhausted: its scope-keyed cooldown
        broker._cooldown[(sid, ("k",))] = (3, time.monotonic())
        assert _req(p, "GET", "/api/v1/readyz")[0] == 503
        code, _, _ = _req(p, "DELETE", f"/api/v1/sessions/{sid}")
        assert code == 200
        # nothing re-probes a deleted scope — delete must purge it
        assert broker._cooldown == {}
        assert _req(p, "GET", "/api/v1/readyz")[0] == 200

    def test_scoped_worker_crash_does_not_disable_shared_speculation(self):
        from kube_scheduler_simulator_tpu.utils import faultinject
        from kube_scheduler_simulator_tpu.utils.broker import CompileBroker

        broker = CompileBroker(speculative=True)
        plane = faultinject.FaultPlane.parse("worker_crash:1.0")
        # a session's pass arms speculation under ITS private fault
        # plane: the crash rides into the worker but is contained to
        # that scope — the shared worker survives, health stays ready
        with faultinject.scoped(plane), telemetry.session_context("s-chaos"):
            assert broker.speculate("t", lambda: None)
        assert broker.drain(timeout=10)
        assert broker.speculative is True  # neighbors keep speculation
        assert broker.worker_crashes == 0  # replica-level health clean
        assert broker.health()["workerCrashed"] is False
        assert broker.stats()["scopedWorkerCrashes"] == 1
        # ...and a later GLOBAL (process-plane) crash still self-disables
        def bad_task():
            raise RuntimeError("real worker bug")

        assert broker.speculate("t2", bad_task)
        assert broker.drain(timeout=10)
        assert broker.speculative is False
        assert broker.worker_crashes == 1

    def test_drop_scope_is_per_scope(self):
        from kube_scheduler_simulator_tpu.utils.broker import CompileBroker

        broker = CompileBroker()
        broker._cooldown[("a", ("k",))] = (2, time.monotonic())
        broker._cooldown[("b", ("k",))] = (2, time.monotonic())
        broker._cooldown[(None, ("k",))] = (2, time.monotonic())  # the sessionless default
        broker.drop_scope("a")
        assert ("a", ("k",)) not in broker._cooldown
        assert ("b", ("k",)) in broker._cooldown
        assert (None, ("k",)) in broker._cooldown


class TestBulkAdmission:
    def test_import_respects_pending_pod_quota(self):
        srv = _server(pending_pod_quota=2)
        try:
            p = srv.port
            sid = _mksession(p)
            snap = {"pods": [pod(f"q{i}") for i in range(3)]}
            code, err, headers = _req(
                p, "POST", f"/api/v1/sessions/{sid}/import", snap
            )
            assert code == 503
            assert err["kind"] == "SessionQuotaExceeded"
            assert headers.get("Retry-After")
            # shed WHOLE: nothing from the snapshot applied
            code, items, _ = _req(
                p, "GET", f"/api/v1/sessions/{sid}/resources/pods"
            )
            assert items["items"] == []
            # bound pods don't count against the pending quota
            snap = {
                "pods": [pod(f"b{i}", node_name="n0") for i in range(5)]
                + [pod("p0")]
            }
            code, out, _ = _req(
                p, "POST", f"/api/v1/sessions/{sid}/import", snap
            )
            assert code == 200, out
        finally:
            srv.shutdown()

    def test_create_snapshot_respects_quota_and_leaves_nothing(self):
        srv = _server(pending_pod_quota=1)
        try:
            p = srv.port
            before = _req(p, "GET", "/api/v1/sessions")[1]
            code, err, _ = _req(
                p,
                "POST",
                "/api/v1/sessions",
                {"snapshot": {"pods": [pod("a"), pod("b")]}},
            )
            assert code == 503 and err["kind"] == "SessionQuotaExceeded"
            after = _req(p, "GET", "/api/v1/sessions")[1]
            assert len(after["sessions"]) == len(before["sessions"])
        finally:
            srv.shutdown()

    def test_auto_schedule_sheds_quietly_at_saturation(self):
        srv = SimulatorServer(
            SimulatorService(),
            port=0,
            auto_schedule=True,
            session_config={"max_concurrent_passes": 1},
        ).start()
        try:
            p = srv.port
            _req(p, "PUT", "/api/v1/resources/nodes", node("n0"))
            baseline = _req(p, "GET", "/api/v1/metrics")[1]["passes"]
            assert srv.sessions._pass_sem.acquire(blocking=False)
            try:
                # the CRUD that triggers the auto-pass SUCCEEDS; only
                # the pass itself is skipped at saturation
                code, _, _ = _req(p, "PUT", "/api/v1/resources/pods", pod("w"))
                assert code == 201
                code, m, _ = _req(p, "GET", "/api/v1/metrics")
                assert m["passes"] == baseline  # shed, not queued
            finally:
                srv.sessions._pass_sem.release()
            # with the slot free the next mutation converges as usual
            code, _, _ = _req(p, "PUT", "/api/v1/resources/pods", pod("w2"))
            assert code == 201
            code, m, _ = _req(p, "GET", "/api/v1/metrics")
            assert m["passes"] == baseline + 1
        finally:
            srv.shutdown()


class TestSnapshotConsistency:
    def test_fork_refused_while_pass_in_flight(self, server):
        p = server.port
        sid = _mksession(p)
        svc = server.sessions.get(sid).service
        assert svc.scheduler._schedule_lock.acquire(blocking=False)
        try:
            code, err, _ = _req(p, "POST", f"/api/v1/sessions/{sid}/fork")
            assert code == 409
            assert err["kind"] == "SessionBusy"
        finally:
            svc.scheduler._schedule_lock.release()
        code, fk, _ = _req(p, "POST", f"/api/v1/sessions/{sid}/fork")
        assert code == 201 and fk["state"] == "live"

    def test_scrape_never_restores_an_evicted_session(self, server):
        p = server.port
        sid = _mksession(p)
        _req(p, "PUT", f"/api/v1/sessions/{sid}/resources/nodes", node("n0"))
        assert _req(p, "POST", f"/api/v1/sessions/{sid}/evict")[0] == 200
        req = urllib.request.Request(
            f"http://127.0.0.1:{p}/api/v1/metrics?format=prometheus"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            families = parse_prometheus_text(resp.read().decode())
        labels = {
            lab.get("session")
            for _, lab, _ in families["kss_passes_total"]["samples"]
        }
        assert sid not in labels  # paused series, not a restore
        code, info, _ = _req(p, "GET", f"/api/v1/sessions/{sid}")
        assert info["state"] == "evicted"  # the scrape did not revive it
