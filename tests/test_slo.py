"""The SLO plane (utils/slo.py, docs/observability.md): the objective
grammar, the multi-window burn-rate alert state machine on a sim-time
clock, the SchedulingMetrics observation funnel, exemplar capture +
OpenMetrics round trip, the HTTP/SSE surfaces, checkpoint continuity,
and the armed-vs-off placement parity pin."""

import json
import threading
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.server import (
    SimulatorServer,
    SimulatorService,
)
from kube_scheduler_simulator_tpu.utils import envcheck
from kube_scheduler_simulator_tpu.utils import metrics as metrics_mod
from kube_scheduler_simulator_tpu.utils import slo, telemetry
from kube_scheduler_simulator_tpu.utils.metrics import (
    METRICS_SCHEMA_VERSION,
    PassRecord,
    SchedulingMetrics,
    parse_prometheus_text,
    render_prometheus,
)

from helpers import node, pod


@pytest.fixture(autouse=True)
def fresh_alert_log():
    log = slo.reset_alert_log(64)
    yield log
    slo.reset_alert_log()


def make_plane(**kw):
    kw.setdefault("session_id", "t")
    kw.setdefault("window_fast_s", 10.0)
    kw.setdefault("window_slow_s", 100.0)
    kw.setdefault("burn_fast", 2.0)
    kw.setdefault("burn_slow", 1.0)
    kw.setdefault("for_s", 0.0)
    return slo.SloPlane(**kw)


# -- the objective grammar ----------------------------------------------------


def test_default_objectives_cover_the_signal_set():
    objs = slo.default_objectives()
    assert set(objs) == set(slo.SIGNALS)
    assert objs["passLatency"].threshold == 1.0
    assert objs["eagerFallback"].threshold is None


def test_parse_objectives_override_and_off():
    objs = slo.parse_objectives(
        "passLatency:target=0.999,threshold=0.5;pendingAge:off"
    )
    assert objs["passLatency"].target == 0.999
    assert objs["passLatency"].threshold == 0.5
    assert "pendingAge" not in objs
    # untouched entries keep their defaults
    assert objs["degradedPass"].target == 0.99


@pytest.mark.parametrize(
    "raw",
    [
        "noSuchSignal:target=0.9",
        "passLatency",  # bare name: no params
        "passLatency:target",  # missing =
        "passLatency:target=nope",
        "passLatency:target=1.5",  # outside (0,1)
        "passLatency:threshold=0",  # must be > 0
        "passLatency:color=red",  # unknown key
    ],
)
def test_parse_objectives_rejects(raw):
    with pytest.raises(ValueError):
        slo.parse_objectives(raw)


def test_envcheck_validates_the_slo_surface():
    ok = {
        "KSS_SLO": "1",
        "KSS_SLO_OBJECTIVES": "passLatency:target=0.999",
        "KSS_SLO_WINDOW_FAST_S": "60",
        "KSS_SLO_ALERT_FOR_S": "0",
        "KSS_EXEMPLARS": "off",
    }
    assert envcheck.check_env(ok) == []
    bad = envcheck.check_env({"KSS_SLO_OBJECTIVES": "bogusSignal:off"})
    assert any("KSS_SLO_OBJECTIVES" in p for p in bad)
    bad = envcheck.check_env({"KSS_SLO_WINDOW_FAST_S": "0.1"})
    assert any("KSS_SLO_WINDOW_FAST_S" in p for p in bad)


def test_objectives_from_spec_mapping_and_rejects():
    objs = slo.objectives_from_spec(
        {"passLatency": {"target": 0.9, "threshold": 0.5},
         "pendingAge": {"off": True}}
    )
    assert objs["passLatency"].target == 0.9
    assert "pendingAge" not in objs
    with pytest.raises(ValueError):
        slo.objectives_from_spec([{"signal": "nope"}])
    with pytest.raises(ValueError):
        slo.objectives_from_spec("not-a-list")


# -- the alert state machine on the sim clock ---------------------------------


def test_alert_lifecycle_pending_firing_resolved(fresh_alert_log):
    plane = make_plane()
    plane.tick_sim(0.0)
    # target 0.99 -> budget 0.01; one bad event burns 100x >> thresholds
    plane.observe("passLatency", value=99.0)
    plane.tick_sim(2.0)  # condition true -> pending
    plane.tick_sim(3.0)  # still true, for_s=0 -> firing
    st = plane.status()["objectives"]["passLatency"]["alert"]["state"]
    assert st == "firing"
    # the fast window (10s) slides past the bad bucket -> resolved
    plane.tick_sim(50.0)
    st = plane.status()["objectives"]["passLatency"]["alert"]["state"]
    assert st == "inactive"
    states = [
        ev["state"]
        for ev in fresh_alert_log.snapshot()
        if ev["objective"] == "passLatency"
    ]
    assert states == ["pending", "firing", "resolved"]
    assert fresh_alert_log.counters()["fired"] == 1
    # transitions carry the judgement context
    firing = [
        ev for ev in fresh_alert_log.snapshot() if ev["state"] == "firing"
    ][0]
    assert firing["session"] == "t"
    assert firing["burnFast"] > 2.0
    assert firing["windowFast"]["bad"] >= 1


def test_pending_hold_and_cancel(fresh_alert_log):
    plane = make_plane(for_s=20.0)
    plane.tick_sim(0.0)
    plane.observe("passLatency", value=99.0)
    plane.tick_sim(2.0)
    assert (
        plane.status()["objectives"]["passLatency"]["alert"]["state"]
        == "pending"
    )
    plane.tick_sim(5.0)  # hold not elapsed: still pending, not firing
    assert (
        plane.status()["objectives"]["passLatency"]["alert"]["state"]
        == "pending"
    )
    # the condition clears before the hold elapses: resolved, never fired
    plane.tick_sim(50.0)
    states = [
        ev["state"]
        for ev in fresh_alert_log.snapshot()
        if ev["objective"] == "passLatency"
    ]
    assert states == ["pending", "resolved"]
    assert fresh_alert_log.counters()["fired"] == 0


def test_both_windows_must_burn():
    # slow window clean -> a fast-only blip must not alert: force the
    # slow burn threshold above what one bad event among many can reach
    plane = make_plane(burn_fast=2.0, burn_slow=60.0)
    plane.tick_sim(0.0)
    for _ in range(99):
        plane.observe("passLatency", value=0.0)
    plane.observe("passLatency", value=99.0)
    plane.tick_sim(2.0)
    # slow burn = (1/100)/0.01 = 1.0 < 60 -> no alert despite fast burn
    assert (
        plane.status()["objectives"]["passLatency"]["alert"]["state"]
        == "inactive"
    )


def test_alert_log_ring_bounded_under_writers():
    log = slo.AlertLog(capacity=8)
    threads = [
        threading.Thread(
            target=lambda k=k: [
                log.emit({"objective": f"o{k}", "state": "firing"})
                for _ in range(50)
            ]
        )
        for k in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert log.emitted == 200
    assert len(log) == 8
    assert log.dropped == 192
    assert log.counters() == {"transitions": 200, "fired": 200}
    seqs = [ev["seq"] for ev in log.snapshot()]
    assert seqs == sorted(seqs) and seqs[-1] == 199


# -- the SchedulingMetrics observation funnel ---------------------------------


def test_metrics_forwarding_covers_every_signal():
    m = SchedulingMetrics()
    plane = make_plane()
    m.set_slo_plane(plane)
    m.record(PassRecord("sequential", 1, 1, 0.01))  # healthy, good latency
    # a degraded pass: the fallback fires MID-pass (the production
    # ordering), its record lands after — ONE ratio event per pass
    m.record_resilience(eager_fallbacks=1, degraded_passes=1)
    m.record(PassRecord("sequential", 1, 1, 9.0))  # bad latency
    m.record_disruption(
        evicted=2, rescheduled=2, times_to_reschedule_s=[1.0, 999.0]
    )
    m.record_pending_age(5.0)
    status = plane.status()["objectives"]
    assert status["passLatency"]["events"] == {"good": 1, "bad": 1}
    # ratio signals: the healthy pass counts good, the degraded pass
    # counts ONLY its bad event (no self-cancelling good)
    assert status["eagerFallback"]["events"] == {"good": 1, "bad": 1}
    assert status["degradedPass"]["events"] == {"good": 1, "bad": 1}
    assert status["timeToReschedule"]["events"] == {"good": 1, "bad": 1}
    assert status["pendingAge"]["events"] == {"good": 1, "bad": 0}


def test_all_degraded_run_reads_zero_compliance():
    """A 100%-degraded run must report compliance 0.0 (one event per
    pass), not the 0.5 a good+bad double count would floor it at."""
    m = SchedulingMetrics()
    plane = make_plane()
    m.set_slo_plane(plane)
    for _ in range(4):
        m.record_resilience(eager_fallbacks=1, degraded_passes=1)
        m.record(PassRecord("sequential", 1, 1, 0.01))
    status = plane.status()["objectives"]
    assert status["degradedPass"]["events"] == {"good": 0, "bad": 4}
    assert status["degradedPass"]["compliance"] == 0.0
    assert status["eagerFallback"]["compliance"] == 0.0
    # latency stayed healthy: the skip is per-objective, not per-pass
    assert status["passLatency"]["events"] == {"good": 4, "bad": 0}


def test_snapshot_slo_block_and_schema_version():
    m = SchedulingMetrics()
    assert METRICS_SCHEMA_VERSION == 4
    snap = m.snapshot()
    assert snap["schemaVersion"] == 4
    assert snap["slo"] == {"enabled": False}
    m.set_slo_plane(make_plane())
    m.record(PassRecord("sequential", 1, 1, 9.0))
    block = m.snapshot()["slo"]
    assert block["enabled"] is True
    assert block["objectives"]["passLatency"]["compliance"] == 0.0
    assert block["objectives"]["passLatency"]["alertState"] in (
        "inactive", "pending", "firing",
    )


def test_env_arming_builds_and_drops_the_plane(monkeypatch):
    m = SchedulingMetrics(session_id="envtest")
    assert m.slo_plane() is None
    monkeypatch.setenv("KSS_SLO", "1")
    plane = m.slo_plane()
    assert plane is not None and plane.session_id == "envtest"
    assert m.slo_plane() is plane  # cached while the env is stable
    monkeypatch.delenv("KSS_SLO")
    assert m.slo_plane() is None
    # explicit override beats the environment
    monkeypatch.setenv("KSS_SLO", "1")
    m.set_slo_plane(None)
    assert m.slo_plane() is None
    m.clear_slo_override()
    assert m.slo_plane() is not None


def test_state_dict_roundtrip_restores_windows_and_alerts(monkeypatch):
    m = SchedulingMetrics()
    plane = make_plane(explicit=True)
    m.set_slo_plane(plane)
    plane.tick_sim(0.0)
    m.record(PassRecord("sequential", 1, 1, 9.0))
    m.record(PassRecord("sequential", 1, 1, 0.01))
    plane.tick_sim(2.0)
    plane.tick_sim(3.0)
    assert (
        plane.status()["objectives"]["passLatency"]["alert"]["state"]
        == "firing"
    )
    state = m.state_dict()
    assert "_slo" in state
    # a fresh registry in a "new process" restores the explicit plane
    m2 = SchedulingMetrics()
    m2.load_state(json.loads(json.dumps(state)))  # through JSON, like disk
    p2 = m2.slo_plane()
    assert p2 is not None and p2.explicit
    status = p2.status()["objectives"]["passLatency"]
    assert status["events"] == {"good": 1, "bad": 1}
    assert status["alert"]["state"] == "firing"
    assert p2.status()["alertsFired"] == 1
    # a non-explicit plane's state only restores while the env arms it
    m3 = SchedulingMetrics()
    st = json.loads(json.dumps(state))
    st["_slo"]["config"]["explicit"] = False
    m3.load_state(st)
    assert m3.slo_plane() is None


def test_restored_env_plane_still_follows_the_env(monkeypatch):
    """A checkpointed ENV-derived plane restores into the env cache
    slot, not as an override: a later KSS_SLO change must still
    rebuild/disarm it (the env-key contract survives resume)."""
    m = SchedulingMetrics()
    plane = make_plane()  # not explicit
    m.set_slo_plane(plane)
    m.record(PassRecord("sequential", 1, 1, 9.0))
    state = json.loads(json.dumps(m.state_dict()))
    assert state["_slo"]["config"]["explicit"] is False
    monkeypatch.setenv("KSS_SLO", "1")
    m2 = SchedulingMetrics()
    m2.load_state(state)
    p2 = m2.slo_plane()
    assert p2 is not None and not p2.explicit
    # the restored window state is live...
    assert (
        p2.status()["objectives"]["passLatency"]["events"]["bad"] == 1
    )
    # ...and turning the env off disarms it — no permanent pin
    monkeypatch.delenv("KSS_SLO")
    assert m2.slo_plane() is None


# -- exemplars ----------------------------------------------------------------


def test_exemplar_capture_and_openmetrics_roundtrip():
    m = SchedulingMetrics(session_id="ex")
    with telemetry.pass_context(7):
        m.record(PassRecord("sequential", 1, 1, 0.15))
    snap = m.snapshot()
    ex = snap["histograms"]["passLatencySeconds"]["exemplars"]
    (le, entry), = ex.items()
    assert entry["labels"] == {"span_id": "7", "session": "ex"}
    assert entry["value"] == 0.15
    text = render_prometheus(snap, openmetrics=True)
    fams = parse_prometheus_text(text)
    exemplars = fams["kss_pass_latency_seconds"]["exemplars"]
    assert len(exemplars) == 1
    name, labels, ex_labels, ex_value = exemplars[0]
    assert name == "kss_pass_latency_seconds_bucket"
    assert labels["le"] == le
    assert ex_labels == {"span_id": "7", "session": "ex"}
    assert ex_value == 0.15
    # the plain prometheus render stays exemplar-free
    assert " # {" not in render_prometheus(snap)


def test_exemplars_disabled_by_env(monkeypatch):
    monkeypatch.setenv("KSS_EXEMPLARS", "0")
    m = SchedulingMetrics()
    with telemetry.pass_context(9):
        m.record(PassRecord("sequential", 1, 1, 0.15))
    assert "exemplars" not in m.snapshot()["histograms"]["passLatencySeconds"]


def test_exemplar_state_rides_histogram_checkpoints():
    m = SchedulingMetrics()
    with telemetry.pass_context(3):
        m.record(PassRecord("sequential", 1, 1, 0.15))
    m2 = SchedulingMetrics()
    m2.load_state(json.loads(json.dumps(m.state_dict())))
    ex = m2.snapshot()["histograms"]["passLatencySeconds"]["exemplars"]
    assert list(ex.values())[0]["labels"]["span_id"] == "3"


def test_parser_rejects_malformed_exemplars():
    good = (
        "# TYPE h histogram\n"
        'h_bucket{le="1.0"} 1 # {span_id="4"} 0.5 1000.0\n'
        'h_bucket{le="+Inf"} 1\n'
        "h_sum 0.5\nh_count 1\n# EOF\n"
    )
    fams = parse_prometheus_text(good)
    assert fams["h"]["exemplars"][0][2] == {"span_id": "4"}
    with pytest.raises(ValueError, match="malformed exemplar"):
        parse_prometheus_text(
            "# TYPE h histogram\n"
            'h_bucket{le="+Inf"} 1 # not-an-exemplar\n'
            "h_sum 0.5\nh_count 1\n"
        )
    with pytest.raises(ValueError, match="non-bucket"):
        parse_prometheus_text(
            "# TYPE c_total counter\n" 'c_total 1 # {span_id="4"} 0.5\n'
        )


def test_parser_tolerates_hash_inside_label_values():
    """'#' is legal inside quoted label values (the 0.0.4 grammar) —
    exemplar detection must not split a sample there."""
    fams = parse_prometheus_text(
        "# TYPE g gauge\n" 'g{lbl="a # b"} 1\n'
    )
    assert fams["g"]["samples"][0][1] == {"lbl": "a # b"}
    # and both at once: a hash-bearing label AND a real exemplar
    fams = parse_prometheus_text(
        "# TYPE h histogram\n"
        'h_bucket{lbl="a # b",le="+Inf"} 1 # {span_id="4"} 0.5\n'
        'h_sum{lbl="a # b"} 0.5\nh_count{lbl="a # b"} 1\n'
    )
    assert fams["h"]["samples"][0][1]["lbl"] == "a # b"
    assert fams["h"]["exemplars"][0][2] == {"span_id": "4"}


# -- the Prometheus families --------------------------------------------------


def test_render_prometheus_planes_through_strict_parse():
    plane = make_plane(session_id="s-1")
    plane.observe("passLatency", value=9.0)
    text = slo.render_prometheus_planes([("s-1", plane), ("s-2", None)])
    fams = parse_prometheus_text(text)
    for fam in (
        "kss_slo_objective_target",
        "kss_slo_compliance",
        "kss_slo_burn_rate_fast",
        "kss_slo_burn_rate_slow",
        "kss_slo_events_total",
        "kss_alert_state",
        "kss_alert_transitions_total",
        "kss_alerts_fired_total",
    ):
        assert fam in fams, fam
    samples = {
        (s[1].get("objective"), s[1].get("result")): s[2]
        for s in fams["kss_slo_events_total"]["samples"]
    }
    assert samples[("passLatency", "bad")] == 1
    # every labeled series names the live session only
    sessions = {
        s[1]["session"]
        for s in fams["kss_slo_compliance"]["samples"]
    }
    assert sessions == {"s-1"}
    # no planes at all: the global ring counters still render
    fams = parse_prometheus_text(slo.render_prometheus_planes([]))
    assert "kss_alert_transitions_total" in fams
    assert "kss_slo_compliance" not in fams


# -- the HTTP / SSE surfaces --------------------------------------------------


def _get(port: int, path: str):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=300
    ) as r:
        return r.status, r.read().decode()


def _req(port: int, path: str, body, method: str):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        method=method,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        return r.status, json.loads(r.read().decode())


@pytest.fixture()
def server():
    srv = SimulatorServer(SimulatorService(), port=0).start()
    try:
        srv.service.store.apply("nodes", node("sn0"))
        srv.service.store.apply("pods", pod("sp0"))
        yield srv
    finally:
        srv.shutdown()


def test_http_slo_put_get_and_alert_fires(server):
    # unarmed: honest empty docs
    _, body = _get(server.port, "/api/v1/alerts")
    assert json.loads(body)["enabled"] is False
    _, body = _get(server.port, "/api/v1/slo")
    assert json.loads(body)["enabled"] is False
    # PUT an explicit override with an unmeetable latency objective
    status, doc = _req(
        server.port,
        "/api/v1/slo",
        {
            "objectives": {
                "passLatency": {"target": 0.99, "threshold": 1e-9}
            },
            "forSeconds": 0,
        },
        "PUT",
    )
    assert status == 200 and doc["enabled"] and doc["explicit"]
    assert doc["objectives"]["passLatency"]["threshold"] == 1e-9
    # two passes + two evaluations (GET /alerts evaluates) walk the
    # state machine to firing
    for _ in range(2):
        server.service.scheduler.schedule()
        _get(server.port, "/api/v1/alerts")
    _, body = _get(server.port, "/api/v1/alerts")
    doc = json.loads(body)
    assert doc["enabled"] is True
    active = {
        (a["objective"], a["state"]) for a in doc["active"]
    }
    assert ("passLatency", "firing") in active
    states = [
        ev["state"]
        for ev in doc["history"]
        if ev["objective"] == "passLatency"
    ]
    assert states[:2] == ["pending", "firing"]
    # the session doc names the default session
    assert "default" in doc["sessions"]
    # prometheus surface carries the families with the firing state
    _, text = _get(server.port, "/api/v1/metrics?format=prometheus")
    fams = parse_prometheus_text(text)
    state_samples = {
        s[1]["objective"]: s[2]
        for s in fams["kss_alert_state"]["samples"]
    }
    assert state_samples["passLatency"] == 2  # firing
    assert fams["kss_alerts_fired_total"]["samples"][0][2] >= 1
    # reset returns to the (unarmed) environment plane
    status, doc = _req(server.port, "/api/v1/slo", {"reset": True}, "PUT")
    assert status == 200 and doc["enabled"] is False


def test_http_slo_rejects_bad_specs(server):
    for body in (
        {"objectives": [{"signal": "nope"}]},
        {"objectives": {"passLatency": {"target": 2.0}}},
        {"windowFastSeconds": 0.0},
    ):
        try:
            _req(server.port, "/api/v1/slo", body, "PUT")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        else:
            raise AssertionError(f"{body} was accepted")


def test_session_create_with_slo_and_nested_routes(server):
    status, doc = _req(
        server.port,
        "/api/v1/sessions",
        {
            "name": "tenant",
            "slo": {
                "objectives": {
                    "passLatency": {"target": 0.9, "threshold": 0.5}
                }
            },
        },
        "POST",
    )
    assert status == 201
    sid = doc["id"]
    _, body = _get(server.port, f"/api/v1/sessions/{sid}/slo")
    nested = json.loads(body)
    assert nested["enabled"] and nested["session"] == sid
    assert nested["objectives"]["passLatency"]["target"] == 0.9
    # the nested alerts route scopes to the tenant
    _, body = _get(server.port, f"/api/v1/sessions/{sid}/alerts")
    doc = json.loads(body)
    assert set(doc["sessions"]) == {sid}
    # the create body honors the FULL PUT /slo shape: forSeconds rides
    # through, and {"enabled": false} means explicitly disarmed
    status, doc = _req(
        server.port,
        "/api/v1/sessions",
        {"slo": {"objectives": None, "forSeconds": 5.5}},
        "POST",
    )
    _, body = _get(server.port, f"/api/v1/sessions/{doc['id']}/slo")
    assert json.loads(body)["forSeconds"] == 5.5
    status, doc = _req(
        server.port, "/api/v1/sessions", {"slo": {"enabled": False}}, "POST"
    )
    _, body = _get(server.port, f"/api/v1/sessions/{doc['id']}/slo")
    assert json.loads(body)["enabled"] is False
    # openmetrics surface stays parseable with the tenant's plane live
    _, text = _get(server.port, "/api/v1/metrics?format=openmetrics")
    assert text.rstrip().endswith("# EOF")
    parse_prometheus_text(text)


def test_session_evict_restore_keeps_explicit_plane(server):
    status, doc = _req(
        server.port,
        "/api/v1/sessions",
        {"slo": {"objectives": {"passLatency": {"threshold": 0.123}}}},
        "POST",
    )
    sid = doc["id"]
    status, _ = _req(server.port, f"/api/v1/sessions/{sid}/evict", {}, "POST")
    assert status == 200
    # the next touch restores the session WITH its explicit plane
    _, body = _get(server.port, f"/api/v1/sessions/{sid}/slo")
    doc = json.loads(body)
    assert doc["enabled"] and doc["explicit"]
    assert doc["objectives"]["passLatency"]["threshold"] == 0.123


def test_sse_alert_event_streams(server):
    _req(
        server.port,
        "/api/v1/slo",
        {
            "objectives": {
                "passLatency": {"target": 0.99, "threshold": 1e-9}
            },
            "forSeconds": 0,
        },
        "PUT",
    )
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/api/v1/events"
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        server.service.scheduler.schedule()
        _get(server.port, "/api/v1/alerts")  # evaluation -> transition
        event = None
        payload = None
        for _ in range(64):
            line = r.readline().decode()
            if line.startswith("event: alert"):
                event = "alert"
                payload = json.loads(
                    r.readline().decode().split(":", 1)[1]
                )
                break
        assert event == "alert"
        assert payload["objective"] == "passLatency"
        assert payload["state"] in ("pending", "firing")


# -- parity + checkpoint continuity over real runs ----------------------------


def _chaos_spec():
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec

    nodes = [
        {
            "metadata": {"name": f"pn{i}"},
            "status": {
                "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}
            },
        }
        for i in range(2)
    ]
    return ChaosSpec.from_dict(
        {
            "name": "slo-parity",
            "seed": 5,
            "horizon": 30.0,
            "schedulerMode": "sequential",
            "pipeline": "sync",
            "snapshot": {"nodes": nodes},
            "arrivals": [
                {
                    "kind": "poisson",
                    "rate": 0.3,
                    "count": 6,
                    "template": {
                        "metadata": {"name": "pchurn"},
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "resources": {
                                        "requests": {"cpu": "100m"}
                                    },
                                }
                            ]
                        },
                    },
                }
            ],
            "faults": [
                {"at": 10.0, "action": "cordon", "node": "pn0"},
                {"at": 20.0, "action": "uncordon", "node": "pn0"},
            ],
        }
    )


def _run_chaos():
    from kube_scheduler_simulator_tpu.lifecycle.engine import (
        LifecycleEngine,
        trace_jsonl,
    )

    eng = LifecycleEngine(_chaos_spec())
    result = eng.run()
    assert result["phase"] == "Succeeded"
    return trace_jsonl(eng.trace), eng


def test_placements_byte_identical_armed_vs_off(monkeypatch):
    off_trace, _ = _run_chaos()
    monkeypatch.setenv("KSS_SLO", "1")
    monkeypatch.setenv("KSS_SLO_OBJECTIVES", "passLatency:threshold=0.001")
    monkeypatch.setenv("KSS_SLO_ALERT_FOR_S", "0")
    armed_trace, eng = _run_chaos()
    # the plane observed and judged...
    block = eng.scheduler.metrics.snapshot()["slo"]
    assert block["enabled"] is True
    events = block["objectives"]["passLatency"]
    assert events["compliance"] < 1.0  # the 1ms threshold was breached
    # ...and the run's decisions are byte-identical (the
    # sampling-invariance acceptance pin)
    assert armed_trace == off_trace


def test_lifecycle_checkpoint_resume_carries_slo_state(
    monkeypatch, tmp_path
):
    """The PR 4/8 continuity contract extended to the SLO plane: a
    checkpointed run's window totals survive into the resumed process's
    plane (through doc["metrics"] -> SchedulingMetrics.load_state)."""
    from kube_scheduler_simulator_tpu.lifecycle.checkpoint import (
        CHECKPOINT_FORMAT,
        load_checkpoint,
    )
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine

    monkeypatch.setenv("KSS_SLO", "1")
    ckpt = str(tmp_path / "slo-ckpt.json")
    eng = LifecycleEngine(
        _chaos_spec(), checkpoint_path=ckpt, stop_after_events=3
    )
    result = eng.run()
    assert result["phase"] == "Interrupted"
    prefix = eng.scheduler.metrics.slo_plane().status()["objectives"][
        "passLatency"
    ]["events"]
    assert prefix["good"] + prefix["bad"] >= 1
    doc = load_checkpoint(ckpt, CHECKPOINT_FORMAT)
    resumed = LifecycleEngine.from_checkpoint(doc)
    result = resumed.run()
    assert result["phase"] == "Succeeded"
    total = resumed.scheduler.metrics.slo_plane().status()["objectives"][
        "passLatency"
    ]["events"]
    # the resumed plane carries the prefix's events plus its own
    assert total["good"] + total["bad"] > prefix["good"] + prefix["bad"]
