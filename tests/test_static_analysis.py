"""kss-lint (kube_scheduler_simulator_tpu/analysis): the tier-1 gate.

Two halves:

  * the LIVE tree must be clean — every cross-cutting contract
    (env registry, metrics registry, jit purity, lock order, span
    balance) holds over the shipped source, with an EMPTY allowlist;
  * every analyzer must FIRE on a synthetic violation — a green gate
    that cannot go red is no gate at all.
"""

import json
import os

import pytest

from kube_scheduler_simulator_tpu.analysis import core
from kube_scheduler_simulator_tpu.analysis import (
    env_registry,
    guarded_state,
    jaxpr_audit,
    jit_purity,
    lock_order,
    metrics_registry,
    span_balance,
    width_class,
)
from kube_scheduler_simulator_tpu.analysis.core import (
    ALLOWLIST,
    Finding,
    RepoContext,
    SourceTree,
    run_all,
)


@pytest.fixture(scope="module")
def live_tree():
    return SourceTree.load()


@pytest.fixture(scope="module")
def live_repo():
    return RepoContext.discover()


def rules_of(findings):
    return {f.rule for f in findings}


# -- the gate -----------------------------------------------------------------


def test_live_tree_is_clean(live_tree, live_repo):
    findings = run_all(live_tree, live_repo)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_allowlist_is_empty():
    # the allowlist exists for emergencies and must stay empty: fix the
    # violation, don't waive it (ISSUE 7 acceptance criterion)
    assert ALLOWLIST == {}


def test_cli_clean_on_live_tree(capsys):
    from kube_scheduler_simulator_tpu.analysis.__main__ import main

    assert main(["--format", "json"]) == 0
    assert json.loads(capsys.readouterr().out) == []


def test_live_lock_graph_is_populated(live_tree):
    # the lock-order analyzer must be analyzing something real: the
    # documented session-plane ordering (state OUTSIDE manager) is a
    # static edge it must see
    edges = {
        (str(a), str(b)) for a, b in lock_order.lock_graph(live_tree)
    }
    assert (
        "server/sessions.py:Session._state_lock",
        "server/sessions.py:SessionManager._lock",
    ) in edges
    assert len(edges) >= 3


def test_live_env_registry_is_populated(live_tree):
    known = env_registry.registry_names(live_tree)
    assert "KSS_LOCK_CHECK" in known  # dogfood: registered in PR 7
    assert "KSS_RACE_CHECK" in known  # dogfood: registered in this PR
    assert "KSS_JAXPR_AUDIT" in known
    assert len(known) >= 15


def test_live_protection_map_is_populated(live_tree):
    # the guarded-state inference must be analyzing something real: the
    # broker's warm-engine map and the service's config are documented
    # lock-claimed state
    pm = guarded_state.protection_map(live_tree)
    broker = pm[("utils/broker.py", "CompileBroker")]
    assert "broker.lock" in broker.claims["_engines"]
    service = pm[("server/service.py", "SchedulerService")]
    assert "service.state" in service.claims["_config"]
    assert sum(len(c.claims) for c in pm.values()) >= 40


# -- negative tests: each analyzer fires on a synthetic violation -------------


def _docs(tmp_path, **files):
    for name, text in files.items():
        (tmp_path / f"{name}.md").write_text(text)
    return RepoContext(docs_dir=str(tmp_path))


def test_env_registry_fires_on_undeclared_read(tmp_path):
    tree = SourceTree.from_sources(
        {
            "utils/envcheck.py": "KNOWN = {\n    'KSS_GOOD': None,\n}\n",
            "server/thing.py": (
                "import os\n"
                "good = os.environ.get('KSS_GOOD')\n"
                "bad = os.environ.get('KSS_BOGUS_KNOB')\n"
            ),
        }
    )
    repo = _docs(tmp_path, **{"environment-variables": "`KSS_GOOD`\n"})
    findings = env_registry.run(tree, repo)
    assert rules_of(findings) == {"KSS101"}
    (f,) = findings
    assert "KSS_BOGUS_KNOB" in f.message and f.path == "server/thing.py"


def test_env_registry_fires_on_dead_and_undocumented_config(tmp_path):
    tree = SourceTree.from_sources(
        {
            "utils/envcheck.py": (
                "KNOWN = {\n"
                "    'KSS_USED': None,\n"
                "    'KSS_DEAD': None,\n"
                "}\n"
            ),
            "server/thing.py": (
                "import os\nused = os.environ.get('KSS_USED')\n"
            ),
        }
    )
    repo = _docs(tmp_path, **{"environment-variables": "`KSS_USED`\n"})
    findings = env_registry.run(tree, repo)
    assert rules_of(findings) == {"KSS102", "KSS103"}
    assert all("KSS_DEAD" in f.message for f in findings)


def test_env_registry_resolves_constants_and_helpers():
    # the two indirect read idioms: a module-level name constant
    # (telemetry's ENV_VAR) and a reader-helper parameter (broker's
    # _env_number) must both count as reads
    tree = SourceTree.from_sources(
        {
            "utils/envcheck.py": "KNOWN = {}\n",
            "a.py": (
                "import os\n"
                "ENV_VAR = 'KSS_BY_CONST'\n"
                "v = os.environ.get(ENV_VAR)\n"
            ),
            "b.py": (
                "import os\n"
                "def _env_number(name, default):\n"
                "    return os.environ.get(name, default)\n"
                "x = _env_number('KSS_BY_HELPER', '1')\n"
            ),
        }
    )
    findings = env_registry.run(tree, RepoContext())
    assert {m for f in findings for m in (f.message,)} == {
        "environment read of KSS_BY_CONST is not declared in "
        "utils/envcheck.KNOWN",
        "environment read of KSS_BY_HELPER is not declared in "
        "utils/envcheck.KNOWN",
    }


def test_metrics_registry_fires_on_undeclared_metric(tmp_path):
    tree = SourceTree.from_sources(
        {"utils/metrics.py": "NAME = 'kss_bogus_total'\n"}
    )
    repo = _docs(
        tmp_path, observability="| `kss_ghost_total` | counter | gone |\n"
    )
    findings = metrics_registry.run(tree, repo)
    assert rules_of(findings) == {"KSS201", "KSS202"}
    by_rule = {f.rule: f for f in findings}
    assert "kss_bogus_total" in by_rule["KSS201"].message
    assert "kss_ghost_total" in by_rule["KSS202"].message


def test_metrics_registry_semantic_render_coverage_fires():
    from kube_scheduler_simulator_tpu.utils.metrics import SchedulingMetrics

    class Unrendered(SchedulingMetrics):
        # a counter that is checkpointed but never rendered: KSS203
        _STATE_FIELDS = SchedulingMetrics._STATE_FIELDS + ("_rogue",)
        _rogue = 0

        def snapshot(self):
            doc = super().snapshot()
            doc["phases"]["rogueCounter"] = self._rogue
            return doc

    findings = metrics_registry.render_coverage_findings(Unrendered)
    assert rules_of(findings) == {"KSS203"}
    assert "rogueCounter" in findings[0].message

    class Unpersisted(SchedulingMetrics):
        # a counter the checkpoint state loses: KSS204
        _lost = 0

        def snapshot(self):
            doc = super().snapshot()
            doc["phases"]["lostCounter"] = self._lost
            return doc

    findings = metrics_registry.render_coverage_findings(Unpersisted)
    assert rules_of(findings) == {"KSS204"}
    assert "lostCounter" in findings[0].message


def test_metrics_registry_semantic_clean_on_live_class():
    assert metrics_registry.render_coverage_findings() == []


def test_jit_purity_fires_on_direct_jax_jit():
    tree = SourceTree.from_sources(
        {
            "engine/thing.py": (
                "import jax\n"
                "def f(x):\n"
                "    return x + 1\n"
                "g = jax.jit(f)\n"
            )
        }
    )
    findings = jit_purity.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS301"}


def test_jit_purity_fires_on_impure_body():
    tree = SourceTree.from_sources(
        {
            "engine/thing.py": (
                "import time\n"
                "import os\n"
                "from ..utils import broker as broker_mod\n"
                "def f(x):\n"
                "    time.sleep(0.1)\n"
                "    v = os.environ.get('HOME')\n"
                "    return x.item()\n"
                "g = broker_mod.jit(f)\n"
            )
        }
    )
    findings = jit_purity.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS302"}
    effects = "\n".join(f.message for f in findings)
    assert "time.sleep" in effects
    assert ".item()" in effects


def test_jit_purity_resolves_builder_closures():
    # the `self.run_fn = self._build_run()` idiom must resolve through
    # the factory's return so the closure body is actually scanned
    tree = SourceTree.from_sources(
        {
            "engine/thing.py": (
                "from ..utils import broker as broker_mod\n"
                "class Engine:\n"
                "    def __init__(self):\n"
                "        self.run_fn = self._build_run()\n"
                "        self._run = broker_mod.jit(self.run_fn)\n"
                "    def _build_run(self):\n"
                "        def run(arrays, state):\n"
                "            print('tracing')\n"
                "            return state\n"
                "        return run\n"
            )
        }
    )
    findings = jit_purity.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS302"}
    assert "print() call" in findings[0].message


def test_lock_order_fires_on_cycle():
    tree = SourceTree.from_sources(
        {
            "server/thing.py": (
                "import threading\n"
                "class T:\n"
                "    def __init__(self):\n"
                "        self._a = threading.Lock()\n"
                "        self._b = threading.Lock()\n"
                "    def one(self):\n"
                "        with self._a:\n"
                "            with self._b:\n"
                "                pass\n"
                "    def two(self):\n"
                "        with self._b:\n"
                "            with self._a:\n"
                "                pass\n"
            )
        }
    )
    findings = lock_order.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS401"}
    assert "T._a" in findings[0].message and "T._b" in findings[0].message


def test_lock_order_one_hop_self_call_edge():
    # evict -> snapshot_dir shape: a method called under a held lock
    # contributes the locks it acquires
    tree = SourceTree.from_sources(
        {
            "server/thing.py": (
                "import threading\n"
                "class T:\n"
                "    def __init__(self):\n"
                "        self._a = threading.Lock()\n"
                "        self._b = threading.Lock()\n"
                "    def helper(self):\n"
                "        with self._b:\n"
                "            pass\n"
                "    def one(self):\n"
                "        with self._a:\n"
                "            self.helper()\n"
                "    def two(self):\n"
                "        with self._b:\n"
                "            with self._a:\n"
                "                pass\n"
            )
        }
    )
    findings = lock_order.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS401"}


def test_span_balance_fires_on_bare_span():
    tree = SourceTree.from_sources(
        {
            "server/thing.py": (
                "from ..utils import telemetry\n"
                "def f():\n"
                "    s = telemetry.span('pass.custom')\n"
                "    s.__enter__()\n"
            )
        }
    )
    findings = span_balance.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS501"}


def test_span_balance_allows_with_and_enter_context():
    tree = SourceTree.from_sources(
        {
            "server/thing.py": (
                "from contextlib import ExitStack\n"
                "from ..utils import telemetry\n"
                "def f():\n"
                "    with telemetry.span('a'), telemetry.span('b'):\n"
                "        pass\n"
                "    with ExitStack() as stack:\n"
                "        stack.enter_context(telemetry.span('c'))\n"
            )
        }
    )
    assert span_balance.run(tree, RepoContext()) == []


def test_span_balance_fires_on_raw_begin_emit():
    tree = SourceTree.from_sources(
        {
            "server/thing.py": (
                "def f(recorder):\n"
                "    recorder.emit({'ph': 'B', 'name': 'x'})\n"
            )
        }
    )
    findings = span_balance.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS502"}


# -- guarded-state (KSS6xx) ---------------------------------------------------


GUARDED_PRELUDE = (
    "from ..utils import locking\n"
    "class T:\n"
    "    def __init__(self):\n"
    "        self._lock = locking.make_lock('t.lock')\n"
    "        self._items = {}\n"
)


def test_guarded_state_fires_on_unguarded_write():
    tree = SourceTree.from_sources(
        {
            "server/thing.py": GUARDED_PRELUDE
            + (
                "    def put(self, k, v):\n"
                "        with self._lock:\n"
                "            self._items[k] = v\n"
                "    def wipe(self):\n"
                "        self._items = {}\n"  # claimed, no lock: KSS601
            )
        }
    )
    findings = guarded_state.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS601"}
    (f,) = findings
    assert "T._items" in f.message and "wipe" in f.message


def test_guarded_state_fires_on_unguarded_read():
    tree = SourceTree.from_sources(
        {
            "server/thing.py": GUARDED_PRELUDE
            + (
                "    def put(self, k, v):\n"
                "        with self._lock:\n"
                "            self._items[k] = v\n"
                "    def peek(self, k):\n"
                "        return self._items.get(k)\n"  # KSS602
            )
        }
    )
    findings = guarded_state.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS602"}


def test_guarded_state_locked_context_fixpoint_is_clean():
    # the _store_locked shape: a helper whose every call site holds the
    # lock is itself a guarded context — claims flow, checks pass
    tree = SourceTree.from_sources(
        {
            "server/thing.py": GUARDED_PRELUDE
            + (
                "    def _store_locked(self, k, v):\n"
                "        self._items[k] = v\n"
                "    def put(self, k, v):\n"
                "        with self._lock:\n"
                "            self._store_locked(k, v)\n"
                "    def get(self, k):\n"
                "        with self._lock:\n"
                "            return self._items.get(k)\n"
            )
        }
    )
    assert guarded_state.run(tree, RepoContext()) == []


def test_guarded_state_acquire_method_counts_as_guarded():
    # the begin_pass shape: a method that .acquire()s the lock is
    # treated as guarded end-to-end (lenient, flow-insensitive)
    tree = SourceTree.from_sources(
        {
            "server/thing.py": GUARDED_PRELUDE
            + (
                "    def put(self, k, v):\n"
                "        with self._lock:\n"
                "            self._items[k] = v\n"
                "    def begin(self):\n"
                "        self._lock.acquire()\n"
                "        self._items['x'] = 1\n"
            )
        }
    )
    assert guarded_state.run(tree, RepoContext()) == []


def test_guarded_state_condition_alias_guards():
    # broker._idle = threading.Condition(self._lock): with self._idle
    # IS holding self._lock
    tree = SourceTree.from_sources(
        {
            "server/thing.py": (
                "import threading\n"
                "from ..utils import locking\n"
                "class T:\n"
                "    def __init__(self):\n"
                "        self._lock = locking.make_lock('t.lock')\n"
                "        self._idle = threading.Condition(self._lock)\n"
                "        self._busy = 0\n"
                "    def work(self):\n"
                "        with self._lock:\n"
                "            self._busy += 1\n"
                "    def drain(self):\n"
                "        with self._idle:\n"
                "            while self._busy:\n"
                "                self._idle.wait(1)\n"
            )
        }
    )
    assert guarded_state.run(tree, RepoContext()) == []


def test_guarded_state_mutator_named_helper_is_a_call_edge():
    # `self.put(...)` is a method CALL on self — a call-graph edge —
    # not a container mutation, even though "put" is a mutator name:
    # the locked call site must keep the helper a guarded context
    tree = SourceTree.from_sources(
        {
            "server/thing.py": GUARDED_PRELUDE
            + (
                "    def put(self, k, v):\n"
                "        self._items[k] = v\n"
                "    def store(self, k, v):\n"
                "        with self._lock:\n"
                "            self.put(k, v)\n"
                "    def get(self, k):\n"
                "        with self._lock:\n"
                "            return self._items.get(k)\n"
            )
        }
    )
    assert guarded_state.run(tree, RepoContext()) == []


def test_guarded_state_mutator_call_is_a_write():
    tree = SourceTree.from_sources(
        {
            "server/thing.py": GUARDED_PRELUDE
            + (
                "    def put(self, k, v):\n"
                "        with self._lock:\n"
                "            self._items.update({k: v})\n"
                "    def evil(self):\n"
                "        self._items.clear()\n"  # mutator, no lock
            )
        }
    )
    findings = guarded_state.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS601"}


def test_guarded_state_closures_are_exempt():
    # nested defs run on other threads / under caller-held locks: the
    # static pass leaves them to the KSS_RACE_CHECK runtime witness
    tree = SourceTree.from_sources(
        {
            "server/thing.py": GUARDED_PRELUDE
            + (
                "    def put(self, k, v):\n"
                "        with self._lock:\n"
                "            self._items[k] = v\n"
                "    def deferred(self):\n"
                "        def finish():\n"
                "            return self._items\n"
                "        return finish\n"
            )
        }
    )
    assert guarded_state.run(tree, RepoContext()) == []


# -- jaxpr-audit static rules (KSS70x) ----------------------------------------


def test_jaxpr_audit_fires_on_callback_api():
    tree = SourceTree.from_sources(
        {
            "engine/thing.py": (
                "import jax\n"
                "def f(x):\n"
                "    jax.debug.print('x={x}', x=x)\n"
                "    return jax.pure_callback(abs, x, x)\n"
            )
        }
    )
    findings = jaxpr_audit.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS701"}
    msgs = "\n".join(f.message for f in findings)
    assert "jax.debug.print" in msgs and "pure_callback" in msgs


def test_jaxpr_audit_fires_on_f64_outside_policy():
    tree = SourceTree.from_sources(
        {
            "engine/thing.py": (
                "import jax.numpy as jnp\n"
                "def f(x):\n"
                "    return x.astype(jnp.float64)\n"
            ),
            # the policy module itself may spell f64
            "engine/encode.py": (
                "import jax.numpy as jnp\nEXACT_F = jnp.float64\n"
            ),
            # EXACT-policy helpers (named *exact*) may too
            "engine/kern.py": (
                "import jax.numpy as jnp\n"
                "def _exact_isqrt64(x):\n"
                "    return x.astype(jnp.float64)\n"
            ),
        }
    )
    findings = jaxpr_audit.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS702"}
    assert all(f.path == "engine/thing.py" for f in findings)


# -- stale allowlist + strict mode (CLI satellites) ---------------------------


def test_stale_waivers_listed_and_nonzero(monkeypatch, capsys):
    from kube_scheduler_simulator_tpu.analysis.__main__ import main

    monkeypatch.setitem(
        core.ALLOWLIST, "KSS999", ("nowhere/ghost.py:1",)
    )
    try:
        rc = main([])
    finally:
        core.ALLOWLIST.pop("KSS999", None)
    err = capsys.readouterr().err
    assert rc == 1
    assert "STALE allowlist entry" in err
    assert "nowhere/ghost.py:1" in err


def test_stale_waivers_helper():
    f = Finding("KSS101", "a.py", 3, "live")
    stale = core.stale_waivers(
        [f], {"KSS101": ("a.py:3", "b.py:9"), "KSS202": ("c.py:1",)}
    )
    assert stale == ["KSS101: b.py:9", "KSS202: c.py:1"]


def test_lint_strict_fails_on_nonempty_allowlist(monkeypatch, capsys, tmp_path):
    from kube_scheduler_simulator_tpu.analysis.__main__ import main

    # a synthetic tree with one real finding, waived: non-strict passes
    # (0 findings survive, the waiver is live), strict refuses
    pkg = tmp_path / "pkg"
    (pkg / "engine").mkdir(parents=True)
    (pkg / "engine" / "bad.py").write_text(
        "import jax\ng = jax.jit(lambda x: x)\n"
    )
    monkeypatch.setitem(core.ALLOWLIST, "KSS301", ("engine/bad.py:2",))
    try:
        monkeypatch.delenv("KSS_LINT_STRICT", raising=False)
        assert main(["--package-dir", str(pkg)]) == 0
        monkeypatch.setenv("KSS_LINT_STRICT", "1")
        assert main(["--package-dir", str(pkg)]) == 1
    finally:
        core.ALLOWLIST.pop("KSS301", None)
    assert "KSS_LINT_STRICT: failing" in capsys.readouterr().err


def test_width_class_fires_on_missing_and_stale_entries():
    tree = SourceTree.from_sources(
        {
            "engine/encode.py": (
                "class ClusterArrays:\n"
                "    declared: object\n"
                "    undeclared: object\n"
                "WIDTH_CLASSES = {\n"
                "    'declared': 'mask',\n"
                "    'ghost': 'id',\n"
                "    'declared_badly': 'huge',\n"
                "}\n"
            ),
        }
    )
    findings = width_class.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS716"}
    messages = "\n".join(f.message for f in findings)
    assert "undeclared" in messages      # field with no width class
    assert "'ghost'" in messages         # stale entry, no such field
    assert "'huge'" in messages          # unknown width class value
    assert "declared_badly" in messages
    assert len(findings) == 4  # 'declared_badly' is both stale AND unknown


def test_width_class_fires_on_missing_dict():
    tree = SourceTree.from_sources(
        {"engine/encode_rel.py": "class PodRelArrays:\n    f: object\n"}
    )
    findings = width_class.run(tree, RepoContext())
    assert rules_of(findings) == {"KSS716"}
    (f,) = findings
    assert "REL_WIDTH_CLASSES" in f.message


def test_width_class_clean_on_total_declaration():
    tree = SourceTree.from_sources(
        {
            "engine/encode.py": (
                "class ClusterArrays:\n"
                "    a: object\n"
                "    b: object\n"
                "    rel: object\n"  # nested plane: carries its own dict
                'WIDTH_CLASSES: "dict[str, str]" = {\n'
                "    'a': 'exact',\n"
                "    'b': 'count',\n"
                "}\n"
            ),
        }
    )
    assert width_class.run(tree, RepoContext()) == []


# -- framework plumbing -------------------------------------------------------


def test_allowlist_filters_by_location():
    f = Finding("KSS999", "a.py", 3, "msg")
    kept = core.apply_allowlist([f], {"KSS999": ("a.py:3",)})
    assert kept == []
    kept = core.apply_allowlist([f], {"KSS999": ("a.py:4",)})
    assert kept == [f]


def test_docstring_literals_are_skipped():
    tree = SourceTree.from_sources(
        {"m.py": '"""mentions kss_fake_total."""\nX = "kss_real_total"\n'}
    )
    names = metrics_registry.source_names(tree)
    assert "kss_real_total" in names
    assert "kss_fake_total" not in names


def test_cli_reports_findings_nonzero(tmp_path, capsys):
    # a package dir with a violation drives exit code 1 through the CLI
    pkg = tmp_path / "pkg"
    (pkg / "engine").mkdir(parents=True)
    (pkg / "engine" / "bad.py").write_text(
        "import jax\ng = jax.jit(lambda x: x)\n"
    )
    from kube_scheduler_simulator_tpu.analysis.__main__ import main

    rc = main(["--package-dir", str(pkg), "--rule", "jit-purity"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "KSS301" in out


def test_finding_render_is_clickable():
    f = Finding("KSS101", "utils/x.py", 12, "boom", hint="fix it")
    assert f.render().startswith("utils/x.py:12: KSS101: boom")
    assert os.linesep not in f.rule
