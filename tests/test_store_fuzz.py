"""Randomized store fuzz: the watch-replay invariant under arbitrary ops.

A watcher that (a) lists-as-ADDED at connect time or (b) replays
`events_since` from any resourceVersion it has seen must reconstruct
EXACTLY the store's final state — this is the contract the SSE
list/watch endpoint, the boot-snapshot reset, and the web UI's live
view all lean on (reference resourcewatcher.go semantics). Directed
cases live in test_store_watch.py; this fuzz drives random interleaved
apply/replace/delete sequences across kinds — ~40% of pods carry
spec.nodeName so node deletes exercise the cascade — and checks:

  * replaying the full event log over an empty dict == final state;
  * resuming from EVERY intermediate resourceVersion reconstructs the
    final state too (replay is suffix-closed);
  * resourceVersions are strictly increasing, one per mutation event;
  * a pruned log raises StaleResourceVersion for pre-window RVs and
    relist-as-ADDED + tail replay still lands on the final state.
"""

import random

import pytest

from kube_scheduler_simulator_tpu.models.store import (
    ResourceStore,
    StaleResourceVersion,
)

KINDS = ("pods", "nodes", "pvcs")


def _obj(kind, name, rng):
    o = {
        "metadata": {"name": name, "labels": {"v": str(rng.randint(0, 9))}},
        "spec": {"x": rng.randint(0, 100)},
    }
    if kind != "nodes":
        o["metadata"]["namespace"] = rng.choice(("default", "kube-sim"))
    if kind == "pods" and rng.random() < 0.4:
        # bound pods make node deletes exercise the cascade path
        o["spec"]["nodeName"] = f"node-{rng.randint(0, 15)}"
    return o


def _replay(events, base=None):
    """Apply watch events over a {kind: {key: obj}} dict."""
    state = {k: dict(v) for k, v in (base or {}).items()}
    for ev in events:
        bucket = state.setdefault(ev.kind, {})
        key = ResourceStore.key(ev.kind, ev.obj)
        if ev.event_type == "DELETED":
            bucket.pop(key, None)
        else:
            bucket[key] = ev.obj
    return state


def _view(state):
    """Non-empty buckets, FULL objects — replay must reconstruct content,
    not just resourceVersions (an event emitting a payload divergent from
    what the store kept at the same RV must fail these assertions)."""
    return {k: v for k, v in state.items() if v}


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_fuzz_watch_replay_reconstructs_state(seed):
    rng = random.Random(seed)
    store = ResourceStore()
    seen_rvs = [0]
    for step in range(300):
        kind = rng.choice(KINDS)
        name = f"{kind[:-1]}-{rng.randint(0, 15)}"
        op = rng.random()
        if op < 0.5:
            store.apply(kind, _obj(kind, name, rng))
        elif op < 0.65:
            # full replacement (no merge) — the other write path
            store.replace(kind, _obj(kind, name, rng))
        elif op < 0.85:
            ns = rng.choice(("default", "kube-sim"))
            if kind == "nodes":
                store.delete(kind, name)  # cascades bound pods
            else:
                store.delete(kind, name, namespace=ns)
        else:
            store.apply(kind, _obj(kind, name, rng))
            seen_rvs.append(store.latest_rv())
    final = {k: {ResourceStore.key(k, o): o for o in store.list(k)} for k in KINDS}

    # full replay from zero
    all_events = []
    for k in KINDS:
        all_events.extend(store.events_since(k, 0))
    all_events.sort(key=lambda e: e.resource_version)
    assert _view(_replay(all_events)) == _view(final)

    # strictly increasing AND contiguous: every mutation in this test
    # lands in one of the collected kinds, so a hole would mean an RV
    # was consumed without emitting its event (one-RV-per-mutation
    # contract)
    rvs = [e.resource_version for e in all_events]
    assert rvs == list(range(rvs[0], rvs[0] + len(rvs))), "RV gap or reorder"

    # resume from every checkpoint RV a watcher might hold: snapshot the
    # state a replay-from-zero reaches AT that RV, then continue with
    # events_since — must land on the final state
    for rv in seen_rvs:
        pre = [e for e in all_events if e.resource_version <= rv]
        post = []
        for k in KINDS:
            post.extend(store.events_since(k, rv))
        post.sort(key=lambda e: e.resource_version)
        assert _view(_replay(post, base=_replay(pre))) == _view(final), rv


def test_fuzz_pruned_log_relist_path():
    rng = random.Random(31)
    store = ResourceStore(event_log_capacity=64)
    for step in range(400):
        kind = rng.choice(KINDS)
        store.apply(kind, _obj(kind, f"o-{rng.randint(0, 30)}", rng))
        if rng.random() < 0.2:
            store.delete(kind, f"o-{rng.randint(0, 30)}",
                         **({} if kind == "nodes" else
                            {"namespace": rng.choice(("default", "kube-sim"))}))
    # an early RV predates the retained window → 410-Gone analogue
    with pytest.raises(StaleResourceVersion):
        store.events_since("pods", 1)
    # the relist path: list-as-ADDED at the current horizon, then replay
    # any tail — reconstructs the final state
    base = {}
    horizon = 0
    for k in KINDS:
        evs = store.list_as_added(k)
        base = _replay(evs, base=base)
        horizon = max([horizon] + [e.resource_version for e in evs])
    store.apply("pods", _obj("pods", "post-relist", rng))
    tail = []
    for k in KINDS:
        tail.extend(store.events_since(k, horizon))
    tail.sort(key=lambda e: e.resource_version)
    final = {k: {ResourceStore.key(k, o): o for o in store.list(k)} for k in KINDS}
    assert _view(_replay(tail, base=base)) == _view(final)


def test_fuzz_snapshot_roundtrip_fixpoint():
    """Checkpoint/resume under random state: export → import into a
    fresh store → export again must be a FIXPOINT (the second snapshot
    equals the first), for stores populated by random interleaved
    apply/replace/delete across every snapshot kind. System objects
    (kube-* / system-* names, kube-system namespace) are filtered on
    the first export, so the fixpoint also proves import introduces no
    new filterable or divergent state. (Directed round-trip cases:
    test_store_snapshot.py; wire-shape pins against the reference's
    documented samples: test_reference_api_samples.py.)"""
    from kube_scheduler_simulator_tpu.models.snapshot import (
        export_snapshot,
        import_snapshot,
    )

    rng = random.Random(51)
    store = ResourceStore()
    kinds = ("pods", "nodes", "pvcs", "pvs", "storageclasses",
             "priorityclasses", "namespaces")
    for _ in range(250):
        kind = rng.choice(kinds)
        prefix = "kube-sys" if rng.random() < 0.1 else "obj"
        name = f"{prefix}-{kind[:-1]}-{rng.randint(0, 12)}"
        o = _obj("pods", name, rng) if kind == "pods" else {
            "metadata": {"name": name},
            "spec": {"x": rng.randint(0, 9)},
        }
        if kind in ("pods", "pvcs"):
            o["metadata"]["namespace"] = rng.choice(("default", "team-a"))
        if rng.random() < 0.75:
            store.apply(kind, o)
        else:
            store.delete(kind, name, **(
                {"namespace": o["metadata"]["namespace"]}
                if kind in ("pods", "pvcs") else {}
            ))
    snap1 = export_snapshot(store, None)
    # not vacuous: the random store exports a real population
    assert sum(len(v) for v in snap1.values() if isinstance(v, list)) > 20
    s2 = ResourceStore()
    _, errs = import_snapshot(s2, snap1)
    assert errs == []
    snap2 = export_snapshot(s2, None)
    assert snap2 == snap1, "export∘import must be a fixpoint"
