from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.models.snapshot import export_snapshot, import_snapshot
from kube_scheduler_simulator_tpu.models.objects import PodView, NodeView, pod_effective_requests
from fractions import Fraction


def make_pod(name, node=None, ns="default", cpu="100m", mem="128Mi"):
    pod = {
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "containers": [
                {"name": "c", "resources": {"requests": {"cpu": cpu, "memory": mem}}}
            ]
        },
    }
    if node:
        pod["spec"]["nodeName"] = node
    return pod


def make_node(name, cpu="4", mem="8Gi", pods="110"):
    return {
        "metadata": {"name": name},
        "status": {"allocatable": {"cpu": cpu, "memory": mem, "pods": pods}},
    }


def test_apply_get_list_delete():
    s = ResourceStore()
    s.apply("pods", make_pod("p1"))
    s.apply("nodes", make_node("n1"))
    assert s.get("pods", "p1") is not None
    assert s.get("pods", "p1")["metadata"]["resourceVersion"] == "1"
    s.apply("pods", make_pod("p1"))  # modify bumps rv
    assert s.get("pods", "p1")["metadata"]["resourceVersion"] == "3"
    assert len(s.list("pods")) == 1
    assert s.delete("pods", "p1")
    assert s.get("pods", "p1") is None


def test_replace_removes_omitted_fields():
    """store.replace is kubectl-replace: the manifest becomes the object
    wholesale (apply's SSA merge would keep removed fields)."""
    s = ResourceStore()
    p = make_pod("p1")
    p["metadata"]["labels"] = {"keep": "no"}
    s.apply("pods", p)
    uid = s.get("pods", "p1")["metadata"]["uid"]
    replacement = make_pod("p1")  # no labels
    out = s.replace("pods", replacement)
    got = s.get("pods", "p1")
    assert "labels" not in got["metadata"]
    # identity is preserved across replaces; RV advances
    assert got["metadata"]["uid"] == uid
    assert int(got["metadata"]["resourceVersion"]) > 1
    assert out["metadata"]["name"] == "p1"
    # replace of a missing object creates it (PUT upsert)
    s.replace("pods", make_pod("fresh"))
    assert s.get("pods", "fresh") is not None


def test_generate_name_suffix_and_collision_redraw(monkeypatch):
    """metadata.generateName gets a random 5-char suffix; a suffix
    collision redraws instead of merging into the existing object
    (the apiserver 409/retry contract)."""
    import random as random_mod

    s = ResourceStore()
    obj = {"metadata": {"generateName": "pod-"}, "spec": {}}
    out = s.apply("pods", dict(obj))
    name1 = out["metadata"]["name"]
    assert name1.startswith("pod-") and len(name1) == len("pod-") + 5
    # force the next draw to collide with name1 first, then yield a
    # fresh suffix — the colliding draw must be skipped
    suffixes = [name1[len("pod-"):], "zzz99"]
    monkeypatch.setattr(
        random_mod, "choices", lambda *a, **k: list(suffixes.pop(0))
    )
    out2 = s.apply("pods", dict(obj))
    assert out2["metadata"]["name"] == "pod-zzz99"
    # the original object was not touched (no MODIFIED merge)
    assert s.get("pods", name1)["metadata"]["name"] == name1
    assert len(s.list("pods")) == 2


def test_node_delete_cascades_pods():
    s = ResourceStore()
    s.apply("nodes", make_node("n1"))
    s.apply("pods", make_pod("p1", node="n1"))
    s.apply("pods", make_pod("p2", node="n2"))
    s.delete("nodes", "n1")
    assert s.get("pods", "p1") is None
    assert s.get("pods", "p2") is not None


def test_watch_events():
    s = ResourceStore()
    seen = []
    s.subscribe(lambda e: seen.append((e.event_type, e.kind)))
    s.apply("pods", make_pod("p1"))
    s.apply("pods", make_pod("p1"))
    s.delete("pods", "p1")
    assert seen == [("ADDED", "pods"), ("MODIFIED", "pods"), ("DELETED", "pods")]
    added = s.list_as_added("pods")
    assert added == []


def test_reset_restores_boot_snapshot():
    s = ResourceStore()
    s.apply("nodes", make_node("boot-node"))
    s.snapshot_initial()
    s.apply("pods", make_pod("later-pod"))
    s.delete("nodes", "boot-node")
    s.reset()
    assert s.get("nodes", "boot-node") is not None
    assert s.get("pods", "later-pod") is None


def test_export_import_roundtrip():
    s = ResourceStore()
    s.apply("namespaces", {"metadata": {"name": "team-a"}})
    s.apply("namespaces", {"metadata": {"name": "kube-system"}})
    s.apply("priorityclasses", {"metadata": {"name": "high"}, "value": 1000})
    s.apply("priorityclasses", {"metadata": {"name": "system-node-critical"}, "value": 2e9})
    s.apply("nodes", make_node("n1"))
    s.apply("pods", make_pod("p1", ns="team-a"))
    s.apply("pvcs", {"metadata": {"name": "claim1", "namespace": "team-a"}, "spec": {}})
    s.apply(
        "pvs",
        {
            "metadata": {"name": "pv1"},
            "spec": {"claimRef": {"name": "claim1", "namespace": "team-a", "uid": "stale"}},
        },
    )
    snap = export_snapshot(s, {"kind": "KubeSchedulerConfiguration"})
    # system objects filtered
    assert [o["metadata"]["name"] for o in snap["namespaces"]] == ["team-a"]
    assert [o["metadata"]["name"] for o in snap["priorityClasses"]] == ["high"]
    assert snap["schedulerConfig"]["kind"] == "KubeSchedulerConfiguration"
    # metadata stripped
    assert "resourceVersion" not in snap["pods"][0]["metadata"]

    s2 = ResourceStore()
    cfg, errs = import_snapshot(s2, snap)
    assert errs == []
    assert cfg["kind"] == "KubeSchedulerConfiguration"
    assert s2.get("pods", "p1", "team-a") is not None
    pv = s2.get("pvs", "pv1")
    pvc = s2.get("pvcs", "claim1", "team-a")
    assert pv["spec"]["claimRef"]["uid"] == pvc["metadata"]["uid"]


def test_pod_views_and_requests():
    pod = {
        "metadata": {"name": "p", "labels": {"app": "web"}},
        "spec": {
            "nodeName": "n1",
            "containers": [
                {"name": "a", "resources": {"requests": {"cpu": "200m", "memory": "1Gi"}}},
                {"name": "b", "resources": {"requests": {"cpu": "300m"}}},
            ],
            "initContainers": [
                {"name": "i", "resources": {"requests": {"cpu": "1", "memory": "64Mi"}}}
            ],
            "overhead": {"cpu": "10m"},
        },
    }
    req = pod_effective_requests(pod)
    # max(sum(containers)=500m, init=1) + overhead 10m = 1.01 cores
    assert req["cpu"] == Fraction(101, 100)
    assert req["memory"] == Fraction(1024**3)
    v = PodView(pod)
    assert v.node_name == "n1"
    assert v.labels == {"app": "web"}
    assert v.num_containers == 2


def test_node_view():
    n = NodeView(make_node("n1", cpu="4", mem="8Gi"))
    assert n.allocatable["cpu"] == 4
    assert n.allocatable["memory"] == Fraction(8 * 1024**3)
    assert not n.unschedulable
