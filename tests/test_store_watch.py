"""Watch-log semantics: events_since, pruning/410-Gone, re-entrant
subscribers (VERDICT round-2 weak #5: these paths had no coverage)."""

import threading

import pytest

from kube_scheduler_simulator_tpu.models.store import (
    ResourceStore,
    StaleResourceVersion,
)

from helpers import node, pod


def test_events_since_filters_by_kind_and_rv():
    s = ResourceStore()
    s.apply("nodes", node("n0"))
    rv_after_node = s.latest_rv()
    s.apply("pods", pod("p0"))
    s.apply("pods", pod("p0", cpu="200m"))  # MODIFIED
    evs = s.events_since("pods", 0)
    assert [e.event_type for e in evs] == ["ADDED", "MODIFIED"]
    assert s.events_since("pods", evs[-1].resource_version) == []
    assert s.events_since("nodes", rv_after_node) == []


def test_prune_raises_stale_and_keeps_recent_window():
    s = ResourceStore(event_log_capacity=10)
    for i in range(15):  # exceed capacity -> older half dropped
        s.apply("pods", pod(f"p{i}"))
    with pytest.raises(StaleResourceVersion):
        s.events_since("pods", 0)
    # a watcher inside the retained window still reads incrementally
    recent = s.events_since("pods", s.latest_rv() - 3)
    assert len(recent) == 3
    # list_as_added still serves the full current state for the relist
    assert len(s.list_as_added("pods")) == 15


def test_reentrant_subscriber_does_not_deadlock_and_orders_events():
    s = ResourceStore()
    seen = []

    def reactive(ev):
        seen.append((ev.event_type, ev.kind, ev.obj["metadata"]["name"]))
        # controller-style reaction: a pod ADDED triggers another apply
        if ev.kind == "pods" and ev.event_type == "ADDED":
            s.apply("nodes", node(f"for-{ev.obj['metadata']['name']}"))

    s.subscribe(reactive)
    s.apply("pods", pod("px"))
    kinds = [k for _, k, _ in seen]
    assert kinds == ["pods", "nodes"]
    assert ("ADDED", "nodes", "for-px") in seen


def test_cross_thread_delivery_order_matches_log():
    s = ResourceStore()
    seen = []
    lock = threading.Lock()

    def sub(ev):
        with lock:
            seen.append(ev.resource_version)

    s.subscribe(sub)
    threads = [
        threading.Thread(
            target=lambda i=i: [s.apply("pods", pod(f"t{i}-{j}")) for j in range(20)]
        )
        for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # delivery preserves log order (dispatch lock serializes drains)
    assert seen == sorted(seen)
    assert len(seen) == 80


def test_concurrent_writers_and_watcher_stress():
    """Race stress (SURVEY §5 thread-safety claim): many writer threads
    applying/deleting while a subscriber consumes. Invariants: every
    subscriber-delivered resourceVersion is unique and monotone per
    delivery order gaps are allowed (writers interleave) but the final
    store state must equal the last write per key, and the event log
    must replay to the same set of live objects."""
    import queue

    store = ResourceStore()
    seen: "queue.Queue" = queue.Queue()
    store.subscribe(seen.put)
    N_THREADS, N_OPS = 8, 60
    errs = []

    def writer(t):
        try:
            for i in range(N_OPS):
                name = f"p-{t}-{i % 10}"
                if i % 7 == 3:
                    store.delete("pods", name, "default")
                else:
                    store.apply(
                        "pods",
                        {
                            "metadata": {"name": name, "namespace": "default"},
                            "spec": {"x": f"{t}-{i}"},
                        },
                    )
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(N_THREADS)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errs, errs
    store.unsubscribe(seen.put)
    # drain: RVs unique and strictly increasing in delivery order
    rvs = []
    while not seen.empty():
        rvs.append(seen.get().resource_version)
    assert len(rvs) == len(set(rvs)), "duplicate resourceVersion delivered"
    assert rvs == sorted(rvs), "subscriber saw events out of order"
    # replaying the retained event log over an empty dict yields exactly
    # the live set (delete events included)
    replayed = {}
    for ev in store.events_since("pods", 0):
        key = (
            ev.obj["metadata"].get("namespace", "default"),
            ev.obj["metadata"]["name"],
        )
        if ev.event_type == "DELETED":
            replayed.pop(key, None)
        else:
            replayed[key] = ev.obj
    live = {
        (p["metadata"]["namespace"], p["metadata"]["name"]): p
        for p in store.list("pods")
    }
    assert set(replayed) == set(live)
    for k in live:
        assert replayed[k]["metadata"]["resourceVersion"] == live[k][
            "metadata"
        ]["resourceVersion"]


def test_unsubscribe_stops_delivery():
    s = ResourceStore()
    seen = []
    fn = seen.append
    s.subscribe(fn)
    s.apply("pods", pod("a"))
    s.unsubscribe(fn)
    s.apply("pods", pod("b"))
    assert len(seen) == 1
