"""The affinity-heavy synthetic workload (BASELINE config #3 shape at
test size): oracle/kernel parity on required anti-affinity chains +
cross-service zone affinity, and a sanity check that the constraints
actually bind (replica spread per hostname)."""

from collections import Counter

from kube_scheduler_simulator_tpu.engine import (
    EXACT,
    BatchedScheduler,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.sched.oracle import Oracle
from kube_scheduler_simulator_tpu.synth import synthetic_affinity_cluster

from test_engine_parity import restricted_config


def _config():
    return restricted_config(
        filters=(
            "NodeUnschedulable",
            "NodeName",
            "NodeResourcesFit",
            "InterPodAffinity",
        ),
        prefilters=("NodeResourcesFit", "InterPodAffinity"),
        scores=(
            ("NodeResourcesFit", 1),
            ("InterPodAffinity", 2),
        ),
        prescores=("NodeResourcesFit", "InterPodAffinity"),
    )


def test_affinity_workload_oracle_parity():
    nodes, pods = synthetic_affinity_cluster(8, 40, seed=2, replicas_per_service=5)
    cfg = _config()
    oracle = Oracle([dict(n) for n in nodes], [dict(p) for p in pods], cfg)
    oracle_res = {
        (r.pod_namespace, r.pod_name): r.selected_node
        for r in oracle.schedule_all()
    }
    sched = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT), record=False
    )
    sched.run()
    assert sched.placements() == oracle_res


def test_anti_affinity_spreads_replicas():
    nodes, pods = synthetic_affinity_cluster(10, 30, seed=4, replicas_per_service=5)
    cfg = _config()
    sched = BatchedScheduler(
        encode_cluster(nodes, pods, cfg, policy=EXACT), record=False
    )
    sched.run()
    placed = sched.placements()
    # per service, no two scheduled replicas share a hostname (node)
    by_svc: dict[str, list[str]] = {}
    for p in pods:
        key = ("default", p["metadata"]["name"])
        if placed[key]:
            by_svc.setdefault(p["metadata"]["labels"]["app"], []).append(
                placed[key]
            )
    assert by_svc, "nothing scheduled"
    for svc, hosts in by_svc.items():
        dupes = [h for h, c in Counter(hosts).items() if c > 1]
        assert not dupes, f"{svc} stacked replicas on {dupes}"
