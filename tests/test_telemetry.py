"""The span flight recorder (utils/telemetry.py): ring bound, on/off
switch, pass-id causality, nesting well-formedness — including under
the async pipelined lifecycle engine, whose three concurrent
machineries are exactly what the recorder exists to make visible.
"""

from __future__ import annotations

import json
import threading

import pytest

from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
from kube_scheduler_simulator_tpu.utils import telemetry

from helpers import node


@pytest.fixture(autouse=True)
def _no_ambient_recorder():
    """Every test starts from the deactivated, env-driven default and
    leaves nothing armed behind (the suite runs with KSS_TRACE scrubbed
    — tests/conftest.py)."""
    telemetry.deactivate()
    yield
    telemetry.deactivate()


class TestRingBuffer:
    def test_bound_holds_under_concurrent_writers(self):
        rec = telemetry.SpanRecorder(capacity=256)
        writers, per_writer = 8, 500

        def hammer(w: int) -> None:
            for i in range(per_writer):
                rec.emit({"ph": "i", "name": f"w{w}", "ts": float(i)})

        threads = [
            threading.Thread(target=hammer, args=(w,)) for w in range(writers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        total = writers * per_writer
        assert rec.emitted == total  # no emission lost from the count
        assert rec.dropped == total - 256
        assert len(rec) == 256  # the bound HELD
        window = rec.snapshot()
        assert len(window) == 256
        assert all(ev is not None for ev in window)

    def test_snapshot_oldest_first_after_wrap(self):
        rec = telemetry.SpanRecorder(capacity=4)
        for i in range(10):
            rec.emit({"seq": i})
        assert [ev["seq"] for ev in rec.snapshot()] == [6, 7, 8, 9]
        assert rec.dropped == 6

    def test_capacity_validation_and_env_fallback(self, monkeypatch):
        with pytest.raises(ValueError):
            telemetry.SpanRecorder(capacity=0)
        monkeypatch.setenv("KSS_TRACE_RING_CAP", "32")
        assert telemetry.ring_capacity_from_env() == 32
        for bad in ("nope", "0", "-5", ""):
            monkeypatch.setenv("KSS_TRACE_RING_CAP", bad)
            assert (
                telemetry.ring_capacity_from_env()
                == telemetry.DEFAULT_RING_CAP
            )

    def test_dead_subscriber_never_breaks_emission(self):
        rec = telemetry.SpanRecorder(capacity=8)
        got = []

        def bad(ev):
            raise RuntimeError("subscriber died")

        rec.subscribe(bad)
        rec.subscribe(got.append)
        rec.emit({"name": "survives"})
        assert [ev["name"] for ev in got] == ["survives"]
        rec.unsubscribe(bad)
        rec.unsubscribe(got.append)
        rec.emit({"name": "after"})
        assert len(got) == 1  # unsubscribed: no longer fed


class TestOnOffSwitch:
    def test_off_by_default_emits_nothing(self, monkeypatch):
        monkeypatch.delenv(telemetry.ENV_VAR, raising=False)
        assert telemetry.active() is None
        assert not telemetry.enabled()
        # the whole emission surface is a no-op with nothing recorded
        with telemetry.span("never", pass_id=3):
            telemetry.instant("never")
        telemetry.complete("never", 0.0, 1.0)

    def test_kss_trace_zero_emits_nothing(self, monkeypatch):
        monkeypatch.setenv(telemetry.ENV_VAR, "0")
        assert telemetry.active() is None
        with telemetry.span("never"):
            pass
        s = telemetry.span("never2")
        assert s is telemetry.span("never3")  # the SHARED no-op span

    def test_env_arms_a_recorder_with_env_capacity(self, monkeypatch):
        monkeypatch.setenv(telemetry.ENV_VAR, "1")
        monkeypatch.setenv(telemetry.CAP_VAR, "64")
        rec = telemetry.active()
        assert rec is not None and rec.capacity == 64
        with telemetry.span("seen"):
            pass
        assert [ev["ph"] for ev in rec.snapshot()] == ["B", "E"]

    def test_activate_overrides_env(self, monkeypatch):
        monkeypatch.setenv(telemetry.ENV_VAR, "0")
        mine = telemetry.SpanRecorder(capacity=16)
        telemetry.activate(mine)
        assert telemetry.active() is mine
        telemetry.instant("mark")
        assert len(mine) == 1
        telemetry.deactivate()
        assert telemetry.active() is None


class TestPassCausality:
    def test_spans_carry_the_current_pass_id(self):
        rec = telemetry.SpanRecorder(capacity=32)
        telemetry.activate(rec)
        with telemetry.pass_context(7):
            assert telemetry.current_pass_id() == 7
            with telemetry.span("inner"):
                telemetry.instant("mark")
            with telemetry.pass_context(8):
                telemetry.instant("nested")
            assert telemetry.current_pass_id() == 7
        assert telemetry.current_pass_id() is None
        passes = [ev["args"].get("pass") for ev in rec.snapshot()]
        assert passes == [7, 7, 7, 8]  # B, i(mark), E, i(nested)

    def test_context_reenters_on_worker_threads(self):
        """The broker's speculation contract: the arming pass's id
        travels to the worker thread and stamps its spans there."""
        rec = telemetry.SpanRecorder(capacity=32)
        telemetry.activate(rec)
        armed_by = 41
        done = threading.Event()

        def worker():
            with telemetry.pass_context(armed_by):
                telemetry.instant("speculative-ish")
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(timeout=10)
        (ev,) = rec.snapshot()
        assert ev["args"]["pass"] == armed_by
        assert ev["tid"] != threading.get_ident()


class TestWellFormedness:
    def test_intervals_and_balanced_nesting(self):
        rec = telemetry.SpanRecorder(capacity=64)
        telemetry.activate(rec)
        with telemetry.span("outer"):
            with telemetry.span("inner"):
                pass
        telemetry.complete("window", 1.0, 2.0, tid=telemetry.DEVICE_TID)
        events = rec.snapshot()
        telemetry.check_nesting(events)  # must not raise
        ivs = {iv["name"]: iv for iv in telemetry.span_intervals(events)}
        assert set(ivs) == {"outer", "inner", "window"}
        assert ivs["window"]["tid"] == telemetry.DEVICE_TID
        assert ivs["window"]["end_us"] - ivs["window"]["start_us"] == 1e6
        assert ivs["inner"]["start_us"] >= ivs["outer"]["start_us"]
        assert ivs["inner"]["end_us"] <= ivs["outer"]["end_us"]

    def test_check_nesting_rejects_malformed(self):
        tid = 9
        with pytest.raises(ValueError, match="unmatched E"):
            telemetry.check_nesting([{"ph": "E", "name": "x", "tid": tid}])
        with pytest.raises(ValueError, match="interleaved"):
            telemetry.check_nesting(
                [
                    {"ph": "B", "name": "a", "tid": tid},
                    {"ph": "B", "name": "b", "tid": tid},
                    {"ph": "E", "name": "a", "tid": tid},
                ]
            )
        with pytest.raises(ValueError, match="unclosed"):
            telemetry.check_nesting([{"ph": "B", "name": "a", "tid": tid}])

    def test_ring_wrapped_window_tolerates_orphan_ends(self):
        """A flight recording longer than the ring starts mid-span: the
        window's leading E events lost their B partners to eviction.
        With the drop count passed, those orphans are tolerated (they
        always land on an empty stack — LIFO closing), while real
        malformations still raise."""
        rec = telemetry.SpanRecorder(capacity=4)
        telemetry.activate(rec)
        with telemetry.span("outer"):
            with telemetry.span("mid"):
                with telemetry.span("inner"):
                    pass
        # capacity 4 kept: E(inner) E(mid) E(outer) preceded by B(inner)
        events = rec.snapshot()
        assert rec.dropped > 0
        with pytest.raises(ValueError, match="unmatched E"):
            telemetry.check_nesting(events)
        telemetry.check_nesting(events, dropped=rec.dropped)  # tolerated
        # interleaving is still a hard error even with drops claimed
        tid = 9
        with pytest.raises(ValueError, match="interleaved"):
            telemetry.check_nesting(
                [
                    {"ph": "B", "name": "a", "tid": tid},
                    {"ph": "B", "name": "b", "tid": tid},
                    {"ph": "E", "name": "a", "tid": tid},
                ],
                dropped=3,
            )

    def test_chrome_trace_export_loadable(self, tmp_path):
        rec = telemetry.SpanRecorder(capacity=32)
        telemetry.activate(rec)
        with telemetry.span("pass.gang", pass_id=1):
            pass
        telemetry.complete(
            "device.execute", 0.5, 1.5, tid=telemetry.DEVICE_TID, pass_id=1
        )
        out = tmp_path / "trace.json"
        n = telemetry.dump_chrome_trace(str(out), rec)
        assert n == 3
        doc = json.loads(out.read_text())
        events = doc["traceEvents"]
        # process metadata + a thread_name per track, device included
        assert any(
            ev["ph"] == "M" and ev["name"] == "process_name" for ev in events
        )
        device_meta = [
            ev
            for ev in events
            if ev["ph"] == "M"
            and ev["name"] == "thread_name"
            and ev["tid"] == telemetry.DEVICE_TID
        ]
        assert device_meta and "device" in device_meta[0]["args"]["name"]
        assert doc["otherData"]["droppedEvents"] == 0


def _chaos_dict() -> dict:
    nodes = [node(f"t{i}", cpu="16", mem="32Gi", pods="110") for i in range(4)]
    return {
        "name": "telemetry-async",
        "seed": 5,
        "horizon": 30.0,
        "schedulerMode": "gang",
        "pipeline": "async",
        "snapshot": {"nodes": nodes},
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 0.8,
                "count": 10,
                "template": {
                    "metadata": {"name": "churn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
        "faults": [
            {"at": 9.0, "action": "cordon", "node": "t0"},
            {"at": 18.0, "action": "uncordon", "node": "t0"},
        ],
    }


class TestUnderAsyncPipeline:
    def test_nesting_balanced_and_passes_stamped(self):
        """The satellite contract: B/E spans stay balanced per thread
        across the async pipeline's dispatch/resolve split, and every
        pass span carries its causal id."""
        rec = telemetry.SpanRecorder(capacity=65536)
        telemetry.activate(rec)
        try:
            eng = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
            res = eng.run()
        finally:
            telemetry.deactivate()
        assert res["phase"] == "Succeeded"
        events = rec.snapshot()
        assert events, "the traced run recorded nothing"
        telemetry.check_nesting(events)  # balanced B/E per thread
        dispatches = [
            ev
            for ev in events
            if ev["ph"] == "B" and ev["name"] == "pass.gang.dispatch"
        ]
        assert dispatches
        ids = [ev["args"]["pass"] for ev in dispatches]
        assert ids == sorted(ids) and len(set(ids)) == len(ids)
        # fault marks landed with sim-time correlation
        faults = [ev for ev in events if ev["name"] == "lifecycle.fault"]
        assert {ev["args"]["action"] for ev in faults} == {
            "cordon",
            "uncordon",
        }
