"""Fleet-wide distributed tracing (docs/observability.md "Distributed
tracing"): the W3C-traceparent-shaped context grammar, thread-local
trace stamping, the clock-offset merge (`merged_chrome_trace` over
skewed per-process clocks), router retry-attempt span trees under
injected net faults, batched-dispatch span links, the per-request ring
+ `kss_fleet_request_seconds` exemplars, the `?worker=` debug proxies,
and the armed-vs-off byte-parity pin — all against in-process workers
(tools/fleet_chaos_smoke.py gate D exercises the spawned-worker,
multi-process path)."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.fleet import FleetRouter
from kube_scheduler_simulator_tpu.server import SimulatorServer, SimulatorService
from kube_scheduler_simulator_tpu.server.batchplane import BatchPlane
from kube_scheduler_simulator_tpu.server.sessions import SessionManager
from kube_scheduler_simulator_tpu.utils import faultinject, telemetry
from kube_scheduler_simulator_tpu.utils.metrics import parse_prometheus_text

from helpers import node, pod


@pytest.fixture(autouse=True)
def _clean_planes():
    """Every test starts with no ambient recorder and no chaos plane,
    and leaves none behind (both are process globals)."""
    telemetry.deactivate()
    faultinject.deactivate()
    yield
    telemetry.deactivate()
    faultinject.deactivate()


def _req(port, method, path, body=None, headers=None, timeout=300):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers=hdrs,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None, dict(resp.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None, dict(e.headers)


def _raw(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=300
    ) as resp:
        return resp.read()


def _ring_entry(port, route, tries=100):
    """The newest ring entry for `route`. The ring records a request
    AFTER its response bytes go out (the recorded total must include
    the relay), so a read racing one's own last request polls briefly."""
    for _ in range(tries):
        _, ring, _ = _req(port, "GET", "/api/v1/fleet/requests")
        hits = [e for e in ring["requests"] if e["route"] == route]
        if hits:
            return ring, hits[-1]
        time.sleep(0.05)
    raise AssertionError(f"no ring entry for {route!r}")


@pytest.fixture()
def traced_fleet(tmp_path):
    """Two in-process workers adopted by a router, with a recorder
    armed — in one process router and workers share it, so the whole
    causal chain of a routed request lands in one ring."""
    rec = telemetry.SpanRecorder(capacity=16384)
    telemetry.activate(rec)
    servers, dirs = [], []
    for i in range(2):
        d = str(tmp_path / f"w{i}")
        srv = SimulatorServer(
            SimulatorService(),
            port=0,
            session_config={"snapshot_dir": d},
        ).start()
        servers.append(srv)
        dirs.append(d)
    router = FleetRouter(
        adopt=[
            (f"http://127.0.0.1:{srv.port}", d)
            for srv, d in zip(servers, dirs)
        ],
        port=0,
        probe_interval_s=60.0,
        fleet_dir=str(tmp_path / "fleet"),
    ).start()
    yield router, servers, rec
    router.shutdown(drain=False)
    for srv in servers:
        try:
            srv.shutdown()
        except Exception:
            pass


class TestTraceparentGrammar:
    def test_round_trip(self):
        tid = telemetry.new_trace_id()
        assert len(tid) == 32 and int(tid, 16) >= 0
        header = telemetry.make_traceparent(tid)
        assert header.startswith("00-") and header.endswith("-01")
        assert telemetry.parse_traceparent(header) == tid

    def test_malformed_degrades_to_untraced(self):
        """A bad header must become an untraced request, never an
        error on the serving path."""
        good = telemetry.make_traceparent(telemetry.new_trace_id())
        for bad in (
            None,
            "",
            "not-a-header",
            good.replace("00-", "ff-"),  # unknown version
            "00-" + "g" * 32 + "-" + "a" * 16 + "-01",  # non-hex trace
            "00-" + "0" * 32 + "-" + "a" * 16 + "-01",  # all-zero trace
            "00-" + "a" * 31 + "-" + "a" * 16 + "-01",  # short trace
            "00-" + "a" * 32 + "-" + "a" * 15 + "-01",  # short parent
            good + "-extra",
        ):
            assert telemetry.parse_traceparent(bad) is None

    def test_propagation_rides_the_recorder_arming(self, monkeypatch):
        monkeypatch.delenv(telemetry.PROPAGATE_VAR, raising=False)
        assert not telemetry.propagate_enabled()  # no recorder, no joins
        telemetry.activate(telemetry.SpanRecorder(capacity=8))
        assert telemetry.propagate_enabled()  # default ON once armed
        monkeypatch.setenv(telemetry.PROPAGATE_VAR, "0")
        assert not telemetry.propagate_enabled()
        monkeypatch.setenv(telemetry.PROPAGATE_VAR, "false")
        assert not telemetry.propagate_enabled()
        monkeypatch.setenv(telemetry.PROPAGATE_VAR, "1")
        assert telemetry.propagate_enabled()


class TestTraceStamping:
    def test_spans_inside_trace_context_carry_the_id(self):
        rec = telemetry.SpanRecorder(capacity=64)
        telemetry.activate(rec)
        tid = telemetry.new_trace_id()
        with telemetry.trace_context(tid):
            assert telemetry.current_trace_id() == tid
            with telemetry.span("traced.work"):
                pass
            telemetry.instant("traced.mark")
        assert telemetry.current_trace_id() is None
        with telemetry.span("untraced.work"):
            pass
        by_name = {}
        for ev in rec.snapshot():
            by_name.setdefault(ev["name"], []).append(ev)
        for name in ("traced.work", "traced.mark"):
            assert all(ev["args"]["trace"] == tid for ev in by_name[name])
        assert all(
            "trace" not in ev["args"] for ev in by_name["untraced.work"]
        )

    def test_explicit_none_trace_is_stripped(self):
        """An untraced async handle passes trace=None explicitly — the
        exported args must not grow a null key."""
        rec = telemetry.SpanRecorder(capacity=8)
        telemetry.activate(rec)
        telemetry.complete("x.window", 0.0, 1.0, tid=telemetry.DEVICE_TID, trace=None)
        (ev,) = rec.snapshot()
        assert "trace" not in ev["args"]

    def test_context_reenters_on_worker_threads(self):
        """Background work a traced request armed re-enters its context
        (broker speculative builds, async resolves)."""
        rec = telemetry.SpanRecorder(capacity=16)
        telemetry.activate(rec)
        tid = telemetry.new_trace_id()
        done = threading.Event()

        def worker():
            with telemetry.trace_context(tid):
                telemetry.instant("bg.work")
            done.set()

        threading.Thread(target=worker).start()
        assert done.wait(timeout=30)
        (ev,) = [e for e in rec.snapshot() if e["name"] == "bg.work"]
        assert ev["args"]["trace"] == tid


def _span(name, ph, ts, pid, tid):
    return {
        "ph": ph,
        "name": name,
        "cat": "kss",
        "ts": float(ts),
        "pid": pid,
        "tid": tid,
        "args": {},
    }


class TestClockOffsetMerge:
    """`merged_chrome_trace` over per-process exports whose monotonic
    clocks share no epoch: a constant per-track shift must land every
    track on the router's timeline with B/E well-formedness intact —
    even when thread ids collide across processes."""

    def _tracks(self):
        # router clock: epoch ~1s. worker clock: epoch ~9s, skewed by
        # -8s so its spans interleave with the router's in merged time.
        # BOTH use tid 7: before (pid, tid)-keyed stacks this would
        # interleave the two processes' B/E pairs into one stack.
        router_events = [
            _span("router.request", "B", 1_000_000, 4242, 7),
            _span("router.attempt", "B", 1_000_100, 4242, 7),
            _span("router.attempt", "E", 1_000_400, 4242, 7),
            _span("router.request", "E", 1_000_500, 4242, 7),
        ]
        worker_events = [
            {
                "ph": "M",
                "name": "thread_name",
                "pid": 9999,
                "tid": 7,
                "args": {"name": "http-worker"},
            },
            _span("pass.sequential", "B", 9_000_150, 9999, 7),
            {
                "ph": "X",
                "name": "device.execute",
                "cat": "kss",
                "ts": 9_000_200.0,
                "dur": 50.0,
                "pid": 9999,
                "tid": 0,
                "args": {},
            },
            _span("pass.sequential", "E", 9_000_300, 9999, 7),
        ]
        return [
            {
                "pid": 0,
                "name": "router",
                "events": router_events,
                "offset_us": 0.0,
            },
            {
                "pid": 1,
                "name": "worker w0",
                "events": worker_events,
                "offset_us": -8_000_000.0,
            },
        ]

    def test_skewed_clocks_merge_into_well_formed_intervals(self):
        doc = telemetry.merged_chrome_trace(self._tracks())
        events = doc["traceEvents"]
        telemetry.check_nesting(events)  # raises on interleaving
        ivals = telemetry.span_intervals(events)
        assert len(ivals) == 4
        assert all(iv["end_us"] >= iv["start_us"] for iv in ivals)
        by_name = {iv["name"]: iv for iv in ivals}
        # the worker track landed on the router's timeline: its pass
        # nests inside the router request's window in merged time
        wpass = by_name["pass.sequential"]
        assert wpass["pid"] == 1
        assert wpass["start_us"] == pytest.approx(1_000_150.0)
        assert (
            by_name["router.request"]["start_us"]
            < wpass["start_us"]
            < wpass["end_us"]
            < by_name["router.request"]["end_us"]
        )
        # device.execute shifted identically (constant per-track shift)
        assert by_name["device.execute"]["start_us"] == pytest.approx(
            1_000_200.0
        )
        # pids remapped to the track lanes, original pids gone
        assert {ev.get("pid") for ev in events} == {0, 1}

    def test_merged_metadata_rebuilt_per_track(self):
        doc = telemetry.merged_chrome_trace(self._tracks(), dropped=3)
        metas = [ev for ev in doc["traceEvents"] if ev["ph"] == "M"]
        procs = {
            ev["pid"]: ev["args"]["name"]
            for ev in metas
            if ev["name"] == "process_name"
        }
        assert procs == {0: "router", 1: "worker w0"}
        # the worker export's own thread label carried over to pid 1
        assert any(
            ev["name"] == "thread_name"
            and ev["pid"] == 1
            and ev["args"]["name"] == "http-worker"
            for ev in metas
        )
        other = doc["otherData"]
        assert other["merged"] is True and other["droppedEvents"] == 3
        assert [t["pid"] for t in other["tracks"]] == [0, 1]

    def test_ring_wrapped_orphan_ends_tolerated_only_when_dropped(self):
        tracks = self._tracks()
        # evict the worker's B: its E arrives on an empty (pid,tid) stack
        tracks[1]["events"] = [
            ev
            for ev in tracks[1]["events"]
            if not (ev["ph"] == "B" and ev["name"] == "pass.sequential")
        ]
        events = telemetry.merged_chrome_trace(tracks)["traceEvents"]
        telemetry.check_nesting(events, dropped=1)
        with pytest.raises(ValueError):
            telemetry.check_nesting(events, dropped=0)


class TestRoutedTraceTree:
    def _drive_session(self, port, sid):
        assert _req(port, "POST", "/api/v1/sessions", {"id": sid})[0] == 201
        base = f"/api/v1/sessions/{sid}"
        _req(port, "PUT", f"{base}/resources/nodes", node("n0", cpu="2"))
        _req(port, "PUT", f"{base}/resources/pods", pod("p0", cpu="500m"))
        return base

    def test_one_trace_id_from_edge_to_device_execute(self, traced_fleet):
        """The tentpole contract: a routed schedule's trace id appears
        on the router request span, its attempt child, the owning
        worker's pass span, AND the device.execute window."""
        router, _servers, rec = traced_fleet
        base = self._drive_session(router.port, "e2e-1")
        code, out, _ = _req(router.port, "POST", f"{base}/schedule")
        assert code == 200 and out["scheduled"] == 1
        ring, entry = _ring_entry(router.port, f"{base}/schedule")
        assert ring["tracing"] is True
        tid = entry["trace"]
        assert tid and len(tid) == 32
        assert entry["attempts"] == 1 and entry["worker"] in ("w0", "w1")
        assert entry["status"] == 200 and entry["breaker"] == "closed"
        # the worker reported its own wall via X-KSS-Worker-Seconds, so
        # the split decomposes: total >= router overhead, worker > 0
        assert entry["workerSeconds"] > 0
        assert entry["totalSeconds"] >= entry["routerSeconds"] >= 0
        assert entry["netSeconds"] >= 0
        traced = [
            ev
            for ev in rec.snapshot()
            if (ev.get("args") or {}).get("trace") == tid
        ]
        names = {(ev["name"], ev["ph"]) for ev in traced}
        assert ("router.request", "B") in names
        assert ("router.attempt", "B") in names
        assert any(
            name.startswith("pass.") and ph == "B" for name, ph in names
        )
        assert ("device.execute", "X") in names

    def test_inbound_traceparent_is_adopted_not_reminted(self, traced_fleet):
        router, _servers, _rec = traced_fleet
        base = self._drive_session(router.port, "adopt-1")
        mine = telemetry.new_trace_id()
        code, _, _ = _req(
            router.port,
            "GET",
            f"{base}/resources/pods",
            headers={"traceparent": telemetry.make_traceparent(mine)},
        )
        assert code == 200
        _, entry = _ring_entry(router.port, f"{base}/resources/pods")
        assert entry["trace"] == mine

    def test_retry_attempts_each_get_a_child_span(self, traced_fleet):
        """Under a total net_drop storm an idempotent GET burns its
        full retry budget — every attempt must be its own child span of
        ONE router request, and the ring must count them."""
        router, _servers, rec = traced_fleet
        base = self._drive_session(router.port, "retry-1")
        faultinject.activate(faultinject.FaultPlane.parse("net_drop:1.0", seed=3))
        try:
            code, _, _ = _req(router.port, "GET", f"{base}/resources/pods")
        finally:
            faultinject.deactivate()
        assert code >= 500  # every attempt dropped
        _, entry = _ring_entry(router.port, f"{base}/resources/pods")
        assert entry["attempts"] == 1 + router.retries
        tid = entry["trace"]
        assert tid
        attempts = [
            ev
            for ev in rec.snapshot()
            if ev["name"] == "router.attempt"
            and ev["ph"] == "B"
            and (ev.get("args") or {}).get("trace") == tid
        ]
        assert len(attempts) == 1 + router.retries
        assert sorted(ev["args"]["attempt"] for ev in attempts) == list(
            range(1, 2 + router.retries)
        )
        # the attempt storm tripped the breaker: the transition is a
        # point event carrying the same causing trace
        opens = [
            ev
            for ev in rec.snapshot()
            if ev["name"] == "router.breaker"
            and ev["args"].get("state") == "open"
        ]
        assert opens and opens[-1]["args"]["trace"] == tid


class TestMergedExportAndProxies:
    def test_merged_trace_federates_all_tracks(self, traced_fleet):
        router, _servers, _rec = traced_fleet
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "mt-1"})[0]
            == 201
        )
        doc = router.merged_trace()
        other = doc["otherData"]
        assert other["merged"] is True and other["tracingEnabled"] is True
        assert [t["pid"] for t in other["tracks"]] == [0, 1, 2]
        assert {t["name"] for t in other["tracks"]} == {
            "router",
            "worker w0",
            "worker w1",
        }
        ivals = telemetry.span_intervals(doc["traceEvents"])
        assert ivals and all(
            iv["end_us"] >= iv["start_us"] for iv in ivals
        )
        # the shared in-process ring reaches every track, so the edge
        # span shows up in worker lanes too — pid remapping held
        assert {iv["pid"] for iv in ivals} <= {0, 1, 2}

    def test_debug_trace_worker_proxy(self, traced_fleet):
        router, _servers, _rec = traced_fleet
        raw = _raw(router.port, "/api/v1/debug/trace?worker=w0")
        doc = json.loads(raw)
        # a single worker's own export: no merge happened
        assert "merged" not in doc["otherData"]
        assert "clockUs" in doc["otherData"]
        code, err, _ = _req(
            router.port, "GET", "/api/v1/debug/trace?worker=nope"
        )
        assert code == 404 and err["kind"] == "UnknownWorker"

    def test_debug_profile_requires_explicit_worker(self, traced_fleet):
        router, _servers, _rec = traced_fleet
        code, err, _ = _req(router.port, "POST", "/api/v1/debug/profile")
        assert code == 400 and err["kind"] == "MissingWorker"
        code, err, _ = _req(
            router.port, "POST", "/api/v1/debug/profile?worker=nope"
        )
        assert code == 404 and err["kind"] == "UnknownWorker"
        # a live target proxies through: the worker answers (no capture
        # running, so stopping is ITS 409 — not a router 4xx)
        code, _, _ = _req(
            router.port,
            "POST",
            "/api/v1/debug/profile?worker=w0",
            {"action": "stop"},
        )
        assert code == 409

    def test_request_ring_feeds_latency_histograms_with_exemplars(
        self, traced_fleet
    ):
        router, _servers, _rec = traced_fleet
        assert (
            _req(router.port, "POST", "/api/v1/sessions", {"id": "hist-1"})[0]
            == 201
        )
        for _ in range(3):
            assert (
                _req(
                    router.port,
                    "GET",
                    "/api/v1/sessions/hist-1/resources/pods",
                )[0]
                == 200
            )
        text = _raw(
            router.port, "/api/v1/metrics?format=openmetrics"
        ).decode()
        families = parse_prometheus_text(text)
        fam = families["kss_fleet_request_seconds"]
        assert fam["type"] == "histogram"
        splits = {
            labels["split"]
            for name, labels, _v in fam["samples"]
            if name.endswith("_count")
        }
        assert splits == {"total", "net", "worker", "router"}
        # every observed request was traced: bucket exemplars link the
        # distribution straight back to trace ids
        assert '# {trace_id="' in text
        # plain prometheus renders the same family without exemplars
        plain = _raw(router.port, "/api/v1/metrics?format=prometheus").decode()
        assert "kss_fleet_request_seconds_bucket" in plain
        assert "# {" not in plain


class TestBatchSpanLinks:
    def _snapshot(self, i):
        return {
            "nodes": [node(f"n{j}", cpu="16") for j in range(3)],
            "pods": [
                pod(f"p{j}", cpu=f"{100 + 100 * i + 50 * j}m")
                for j in range(4)
            ],
        }

    def test_one_dispatch_links_every_enrolled_trace(self):
        """The batch plane executes N tenants' passes as ONE device
        dispatch — a single span can't carry one trace id, so the
        `batch.execute` complete carries span LINKS to every enrolled
        tenant's trace instead."""
        rec = telemetry.SpanRecorder(capacity=4096)
        telemetry.activate(rec)
        mgr = SessionManager(
            SimulatorService(), max_sessions=8, max_concurrent_passes=8
        )
        plane = BatchPlane(
            window_ms=5000.0,
            max_sessions=2,
            metrics=mgr.get("default").service.scheduler.metrics,
        )
        mgr.batch_plane = plane
        mgr.get("default").service.scheduler.batch_plane = plane
        try:
            sessions = []
            for i in range(2):
                sess, errs = mgr.create(
                    name=f"link{i}", snapshot=self._snapshot(i)
                )
                assert not errs
                sessions.append(sess)
            tids = [telemetry.new_trace_id() for _ in range(2)]
            barrier = threading.Barrier(2)
            errors = {}

            def run(i):
                try:
                    barrier.wait(timeout=30)
                    with telemetry.trace_context(tids[i]), mgr.pass_slot():
                        sessions[i].service.scheduler.schedule()
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors[i] = repr(e)

            threads = [
                threading.Thread(target=run, args=(i,)) for i in range(2)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert not errors, errors
        finally:
            mgr.shutdown()
        execs = [
            ev
            for ev in rec.snapshot()
            if ev["name"] == "batch.execute" and ev["ph"] == "X"
        ]
        assert len(execs) == 1  # the window filled: ONE device dispatch
        assert execs[0]["args"]["links"] == sorted(tids)
        assert execs[0]["args"]["fill"] == 2


class TestArmedVsOffByteParity:
    def _drive(self, tmp_path, name, traced):
        srv = SimulatorServer(
            SimulatorService(),
            port=0,
            session_config={"snapshot_dir": str(tmp_path / name)},
        ).start()
        try:
            headers = (
                {
                    "traceparent": telemetry.make_traceparent(
                        telemetry.new_trace_id()
                    )
                }
                if traced
                else None
            )
            assert (
                _req(
                    srv.port,
                    "POST",
                    "/api/v1/sessions",
                    {"id": "parity-t"},
                    headers=headers,
                )[0]
                == 201
            )
            base = "/api/v1/sessions/parity-t"
            for i in range(2):
                _req(
                    srv.port,
                    "PUT",
                    f"{base}/resources/nodes",
                    node(f"n{i}", cpu="2", mem="4Gi"),
                    headers=headers,
                )
            for i in range(4):
                _req(
                    srv.port,
                    "PUT",
                    f"{base}/resources/pods",
                    pod(f"p{i}", cpu="500m", mem="512Mi"),
                    headers=headers,
                )
            code, out, _ = _req(
                srv.port, "POST", f"{base}/schedule", headers=headers
            )
            assert code == 200 and out["scheduled"] == 4
            return _raw(srv.port, f"{base}/resources/pods")
        finally:
            srv.shutdown()

    def test_placements_and_trace_bytes_identical(self, tmp_path):
        """The whole plane is observability: with KSS_TRACE=0 it must
        be a no-op, and arming it must not perturb a single placement
        or scheduling-trace annotation byte."""
        telemetry.activate(None)  # tracing explicitly OFF
        off = self._drive(tmp_path, "off", traced=False)
        rec = telemetry.SpanRecorder(capacity=16384)
        telemetry.activate(rec)
        armed = self._drive(tmp_path, "armed", traced=True)
        assert rec.emitted > 0  # the armed run really recorded
        assert off == armed
