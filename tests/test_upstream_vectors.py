"""Upstream-anchored behavior vectors (VERDICT r4 #8: de-correlate the
parity oracle).

Every other parity suite asserts oracle == engine — but both sides share
one author's reading of upstream v1.26, so a correlated misreading
passes every test. The vectors here pin EXPECTED OUTCOMES derived from
the upstream kube-scheduler's own unit-test semantics for the three
hardest plugins, hand-transcribed (this build has no network, so from
the well-known public v1.26 test families, cited by upstream test
function), and assert BOTH the oracle AND the engine reproduce them —
the expected values never come from running either implementation.

Upstream anchors:
  * PodTopologySpread —
    pkg/scheduler/framework/plugins/podtopologyspread/filtering_test.go
    (TestSingleConstraint, TestMultipleConstraints): feasibility iff
    matchNum + 1 - minMatchNum <= maxSkew over eligible domains; nodes
    without the topology key are infeasible for DoNotSchedule
    constraints; ScheduleAnyway never filters; the incoming pod itself
    never counts; namespace-scoped matching.
  * InterPodAffinity —
    pkg/scheduler/framework/plugins/interpodaffinity/filtering_test.go
    (TestRequiredAffinitySingleNode, TestRequiredAffinityMultipleNodes):
    required affinity restricts to domains holding a match (self-match
    special case when nothing matches anywhere); required anti-affinity
    excludes domains holding a match, including SYMMETRICALLY from
    existing pods' anti-affinity; default namespace scoping is the
    incoming pod's namespace.
  * DefaultPreemption —
    pkg/scheduler/framework/preemption (TestDryRunPreemption,
    TestSelectBestCandidate semantics): victims = lower-priority pods
    minus highest-priority-first reprieves; candidate ranking = min
    highest-victim-priority, then min priority sum, then fewest victims.
"""

from __future__ import annotations

import json

from kube_scheduler_simulator_tpu.engine import (
    EXACT,
    BatchedScheduler,
    encode_cluster,
)
from kube_scheduler_simulator_tpu.sched.oracle import Oracle

from helpers import node, pod
from test_engine_parity_interpod import aff, ipa_config, term
from test_engine_parity_preempt import preempt_config
from test_engine_parity_spread import spread_config

ZONE = "topology.kubernetes.io/zone"
HOST = "kubernetes.io/hostname"


def znode(name, zone, host=True, cpu="16", **kw):
    labels = {ZONE: zone}
    if host:
        labels[HOST] = name
    labels.update(kw.pop("extra_labels", {}))
    return node(name, cpu=cpu, labels=labels, **kw)


def run_both(nodes, pods_, cfg, **enc_kw):
    """Run oracle and engine; return (oracle_records, engine_records)
    keyed (ns, name) -> LIST of records in emission order."""
    oracle = Oracle(
        [dict(n) for n in nodes], [dict(p) for p in pods_], cfg,
        **{k: [dict(o) for o in v] for k, v in enc_kw.items()},
    )
    want = oracle.schedule_all()
    enc = encode_cluster(nodes, pods_, cfg, policy=EXACT, **enc_kw)
    eng = BatchedScheduler(enc)
    got = eng.results()

    def by_pod(rs):
        out: dict = {}
        for r in rs:
            out.setdefault((r.pod_namespace, r.pod_name), []).append(r)
        return out

    return by_pod(want), by_pod(got)


def plugin_verdicts(rec, plugin) -> dict:
    """node -> True (passed) / False (failed) / None (not evaluated —
    an earlier filter already rejected the node)."""
    raw = rec.to_annotations()["scheduler-simulator/filter-result"]
    table = json.loads(raw) if raw else {}
    out = {}
    for node_name, plugins in table.items():
        if plugin in plugins:
            out[node_name] = plugins[plugin] == "passed"
        else:
            out[node_name] = None
    return out


def assert_filter_vector(nodes, pods_, cfg, test_pod, expect_feasible, plugin,
                         **enc_kw):
    """The vector contract: for BOTH implementations, `plugin` passes
    exactly on `expect_feasible` (nodes the plugin rejected must carry a
    failure verdict; nodes it passed must carry 'passed'), and the
    selected node is inside the feasible set (or the pod is
    Unschedulable when the set is empty)."""
    want, got = run_both(nodes, pods_, cfg, **enc_kw)
    all_nodes = {n["metadata"]["name"] for n in nodes}
    expect_feasible = set(expect_feasible)
    for impl, recs in (("oracle", want), ("engine", got)):
        rec = recs[("default", test_pod)][-1]
        verdicts = plugin_verdicts(rec, plugin)
        feasible = {n for n, v in verdicts.items() if v}
        infeasible = {n for n, v in verdicts.items() if v is False}
        assert feasible == expect_feasible, (
            impl, sorted(feasible), sorted(expect_feasible))
        assert infeasible == all_nodes - expect_feasible, (
            impl, sorted(infeasible))
        if expect_feasible:
            assert rec.status == "Scheduled", (impl, rec.status)
            assert rec.selected_node in expect_feasible, (
                impl, rec.selected_node)
        else:
            assert rec.status == "Unschedulable", (impl, rec.status)


# ---------------------------------------------------------------------------
# PodTopologySpread (upstream filtering_test.go TestSingleConstraint /
# TestMultipleConstraints)
# ---------------------------------------------------------------------------


def spread_pod(name, constraints, labels=None, **kw):
    return pod(name, labels=labels or {"app": "web"}, spread=constraints, **kw)


def zone_constraint(max_skew=1, when="DoNotSchedule", key=ZONE, app="web"):
    return {
        "maxSkew": max_skew,
        "topologyKey": key,
        "whenUnsatisfiable": when,
        "labelSelector": {"matchLabels": {"app": app}},
    }


def three_zones():
    return [znode(f"n-{z}", z) for z in ("a", "b", "c")]


def web(name, node_name, ns="default", app="web"):
    return pod(name, ns=ns, labels={"app": app}, node_name=node_name)


class TestSpreadVectors:
    PLUGIN = "PodTopologySpread"

    def test_no_existing_pods_all_feasible(self):
        # upstream TestSingleConstraint "no existing pods"
        assert_filter_vector(
            three_zones(), [spread_pod("t", [zone_constraint()])],
            spread_config(), "t", {"n-a", "n-b", "n-c"}, self.PLUGIN)

    def test_skew_one_only_min_zone_feasible(self):
        # upstream "existing pods in a different namespace doesn't count"
        # sibling case "normal case": counts a=2 b=1 c=0, min=0,
        # feasible iff count+1-0 <= 1 -> only c
        pods_ = [web("e0", "n-a"), web("e1", "n-a"), web("e2", "n-b"),
                 spread_pod("t", [zone_constraint()])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t", {"n-c"}, self.PLUGIN)

    def test_max_skew_two_widens_feasible_set(self):
        pods_ = [web("e0", "n-a"), web("e1", "n-a"), web("e2", "n-b"),
                 spread_pod("t", [zone_constraint(max_skew=2)])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t", {"n-b", "n-c"},
            self.PLUGIN)

    def test_balanced_counts_all_feasible(self):
        pods_ = [web("e0", "n-a"), web("e1", "n-b"), web("e2", "n-c"),
                 spread_pod("t", [zone_constraint()])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t",
            {"n-a", "n-b", "n-c"}, self.PLUGIN)

    def test_schedule_anyway_never_filters(self):
        # upstream: ScheduleAnyway constraints are scoring-only
        pods_ = [web("e0", "n-a"), web("e1", "n-a"),
                 spread_pod("t", [zone_constraint(when="ScheduleAnyway")])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t",
            {"n-a", "n-b", "n-c"}, self.PLUGIN)

    def test_non_matching_existing_pods_dont_count(self):
        pods_ = [web("e0", "n-a", app="db"), web("e1", "n-a", app="db"),
                 spread_pod("t", [zone_constraint()])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t",
            {"n-a", "n-b", "n-c"}, self.PLUGIN)

    def test_other_namespace_doesnt_count(self):
        # upstream "existing pods in a different namespace doesn't count"
        pods_ = [web("e0", "n-a", ns="other"), web("e1", "n-a", ns="other"),
                 spread_pod("t", [zone_constraint()])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t",
            {"n-a", "n-b", "n-c"}, self.PLUGIN)

    def test_node_missing_topology_key_infeasible(self):
        # upstream: a node without the constraint's topologyKey cannot
        # satisfy a DoNotSchedule constraint
        nodes = three_zones() + [node("n-x", cpu="16", labels={HOST: "n-x"})]
        assert_filter_vector(
            nodes, [spread_pod("t", [zone_constraint()])],
            spread_config(), "t", {"n-a", "n-b", "n-c"}, self.PLUGIN)

    def test_hostname_constraint_spreads_per_node(self):
        nodes = [znode(f"n{i}", "a") for i in range(4)]
        pods_ = [web("e0", "n0"), web("e1", "n0"), web("e2", "n1"),
                 spread_pod("t", [zone_constraint(key=HOST)])]
        assert_filter_vector(
            nodes, pods_, spread_config(), "t", {"n2", "n3"}, self.PLUGIN)

    def test_two_constraints_intersect(self):
        # upstream TestMultipleConstraints: zone constraint allows only
        # zone c; hostname constraint excludes n-c0 (has a pod) -> n-c1
        nodes = [znode("n-a0", "a"), znode("n-b0", "b"),
                 znode("n-c0", "c"), znode("n-c1", "c")]
        pods_ = [web("e0", "n-a0"), web("e1", "n-a0"), web("e2", "n-b0"),
                 web("e3", "n-c0"),
                 # zone counts a=2 b=1 c=1 min=1: feasible zones b
                 # (1+1-1<=1) and c; hostname counts n-a0=2 n-b0=1
                 # n-c0=1 n-c1=0 min=0: feasible hosts only n-c1
                 spread_pod("t", [zone_constraint(),
                                  zone_constraint(key=HOST)])]
        assert_filter_vector(
            nodes, pods_, spread_config(), "t", {"n-c1"}, self.PLUGIN)

    def test_incoming_pod_never_counts_itself(self):
        # upstream: only EXISTING pods count toward matchNum
        pods_ = [spread_pod("t", [zone_constraint()])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t",
            {"n-a", "n-b", "n-c"}, self.PLUGIN)

    def test_three_in_one_zone_rest_feasible(self):
        pods_ = [web(f"e{i}", "n-a") for i in range(3)] + [
            spread_pod("t", [zone_constraint()])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t", {"n-b", "n-c"},
            self.PLUGIN)

    def test_min_over_domains_counts_empty_zone(self):
        # min is over DOMAINS (zones with eligible nodes), so an empty
        # zone keeps min=0 and blocks zones at the skew edge
        pods_ = [web("e0", "n-a"), spread_pod("t", [zone_constraint()])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t", {"n-b", "n-c"},
            self.PLUGIN)

    def test_large_max_skew_all_feasible(self):
        pods_ = [web(f"e{i}", "n-a") for i in range(4)] + [
            spread_pod("t", [zone_constraint(max_skew=10)])]
        assert_filter_vector(
            three_zones(), pods_, spread_config(), "t",
            {"n-a", "n-b", "n-c"}, self.PLUGIN)

    def test_two_per_zone_balanced_feasible(self):
        nodes = [znode("n-a0", "a"), znode("n-a1", "a"),
                 znode("n-b0", "b"), znode("n-b1", "b")]
        pods_ = [web("e0", "n-a0"), web("e1", "n-a1"),
                 web("e2", "n-b0"), web("e3", "n-b1"),
                 spread_pod("t", [zone_constraint()])]
        assert_filter_vector(
            nodes, pods_, spread_config(), "t",
            {"n-a0", "n-a1", "n-b0", "n-b1"}, self.PLUGIN)


# ---------------------------------------------------------------------------
# InterPodAffinity (upstream filtering_test.go TestRequiredAffinity*)
# ---------------------------------------------------------------------------


def four_zone_nodes():
    return [znode("n-a0", "a"), znode("n-a1", "a"),
            znode("n-b0", "b"), znode("n-b1", "b")]


class TestInterPodAffinityVectors:
    PLUGIN = "InterPodAffinity"

    def test_required_affinity_restricts_to_matching_zone(self):
        # upstream TestRequiredAffinitySingleNode: pod requires affinity
        # to app=s1 over zone; a bound s1 pod sits in zone a
        pods_ = [pod("e0", labels={"app": "s1"}, node_name="n-a0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(required=[term("s1")]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", {"n-a0", "n-a1"},
            self.PLUGIN)

    def test_required_affinity_no_match_unschedulable(self):
        # no pod matches, selector does not match self -> nowhere
        pods_ = [pod("e0", labels={"app": "other"}, node_name="n-a0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(required=[term("s1")]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", set(), self.PLUGIN)

    def test_self_match_special_case_allows_first_in_series(self):
        # upstream filtering.go: required affinity whose selector
        # matches the incoming pod's OWN labels passes when nothing
        # matches anywhere (the first pod of a self-affine series)
        pods_ = [pod("t", labels={"app": "s1"},
                     affinity=aff(required=[term("s1")]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t",
            {"n-a0", "n-a1", "n-b0", "n-b1"}, self.PLUGIN)

    def test_self_match_not_used_when_real_match_exists(self):
        # once a real match exists, its domain governs even for a
        # self-matching selector
        pods_ = [pod("e0", labels={"app": "s1"}, node_name="n-b0"),
                 pod("t", labels={"app": "s1"},
                     affinity=aff(required=[term("s1")]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", {"n-b0", "n-b1"},
            self.PLUGIN)

    def test_required_anti_affinity_excludes_matching_zone(self):
        pods_ = [pod("e0", labels={"app": "s1"}, node_name="n-a0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(anti_required=[term("s1")]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", {"n-b0", "n-b1"},
            self.PLUGIN)

    def test_symmetric_anti_affinity_from_existing_pod(self):
        # upstream symmetry: an EXISTING pod's required anti-affinity
        # matching the incoming pod blocks the existing pod's domain
        pods_ = [pod("e0", labels={"app": "guard"}, node_name="n-a0",
                     affinity=aff(anti_required=[term("t")])),
                 pod("t", labels={"app": "t"})]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", {"n-b0", "n-b1"},
            self.PLUGIN)

    def test_positive_affinity_is_not_symmetric_for_filtering(self):
        # upstream: an existing pod's required POSITIVE affinity never
        # filters incoming pods (symmetry applies to scoring only)
        pods_ = [pod("e0", labels={"app": "lonely"}, node_name="n-a0",
                     affinity=aff(required=[term("ghost")])),
                 pod("t", labels={"app": "t"})]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t",
            {"n-a0", "n-a1", "n-b0", "n-b1"}, self.PLUGIN)

    def test_hostname_affinity_pins_to_node(self):
        pods_ = [pod("e0", labels={"app": "s1"}, node_name="n-a0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(required=[term("s1", key=HOST)]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", {"n-a0"},
            self.PLUGIN)

    def test_hostname_anti_affinity_excludes_only_that_node(self):
        pods_ = [pod("e0", labels={"app": "s1"}, node_name="n-a0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(anti_required=[term("s1", key=HOST)]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t",
            {"n-a1", "n-b0", "n-b1"}, self.PLUGIN)

    def test_default_namespace_scoping_ignores_other_ns(self):
        # upstream: a term without namespaces matches only the incoming
        # pod's own namespace
        pods_ = [pod("e0", ns="other", labels={"app": "s1"},
                     node_name="n-a0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(required=[term("s1")]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", set(), self.PLUGIN)

    def test_explicit_namespaces_match_other_ns(self):
        pods_ = [pod("e0", ns="other", labels={"app": "s1"},
                     node_name="n-a0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(required=[term("s1", ns=["other"])]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", {"n-a0", "n-a1"},
            self.PLUGIN)

    def test_anti_affinity_default_ns_scoping(self):
        # matching pod lives in another namespace -> does not block
        pods_ = [pod("e0", ns="other", labels={"app": "s1"},
                     node_name="n-a0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(anti_required=[term("s1")]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t",
            {"n-a0", "n-a1", "n-b0", "n-b1"}, self.PLUGIN)

    def test_multiple_required_terms_intersect(self):
        pods_ = [pod("e0", labels={"app": "s1"}, node_name="n-a0"),
                 pod("e1", labels={"app": "s2"}, node_name="n-a1"),
                 pod("e2", labels={"app": "s2"}, node_name="n-b0"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(required=[term("s1"), term("s2")]))]
        # s1 in zone a only; s2 in both -> intersection = zone a
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", {"n-a0", "n-a1"},
            self.PLUGIN)

    def test_affinity_and_anti_affinity_can_conflict(self):
        pods_ = [pod("e0", labels={"app": "want"}, node_name="n-a0"),
                 pod("e1", labels={"app": "avoid"}, node_name="n-a1"),
                 pod("t", labels={"app": "t"},
                     affinity=aff(required=[term("want")],
                                  anti_required=[term("avoid")]))]
        # want restricts to zone a; avoid excludes zone a -> nowhere
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t", set(), self.PLUGIN)

    def test_preferred_terms_never_filter(self):
        pods_ = [pod("t", labels={"app": "t"},
                     affinity=aff(preferred=[{
                         "weight": 100,
                         "podAffinityTerm": term("nobody"),
                     }]))]
        assert_filter_vector(
            four_zone_nodes(), pods_, ipa_config(), "t",
            {"n-a0", "n-a1", "n-b0", "n-b1"}, self.PLUGIN)

    def test_anti_affinity_series_spreads_zones(self):
        # self-matching anti-affinity: the classic one-per-zone series
        pods_ = [pod(f"t{i}", labels={"app": "t"},
                     affinity=aff(anti_required=[term("t")]))
                 for i in range(3)]
        want, got = run_both(four_zone_nodes(), pods_, ipa_config())
        for impl, recs in (("oracle", want), ("engine", got)):
            sel = {name: recs[("default", f"t{i}")][-1].selected_node
                   for i, name in enumerate(["t0", "t1", "t2"])}
            assert recs[("default", "t0")][-1].status == "Scheduled"
            assert recs[("default", "t1")][-1].status == "Scheduled"
            # two zones -> third pod has nowhere
            assert recs[("default", "t2")][-1].status == "Unschedulable", impl
            zones = {sel["t0"][2], sel["t1"][2]}
            assert zones == {"a", "b"}, (impl, sel)


# ---------------------------------------------------------------------------
# DefaultPreemption (upstream preemption_test.go TestDryRunPreemption /
# TestSelectBestCandidate semantics)
# ---------------------------------------------------------------------------


def preempt_cluster(specs):
    """specs: {node: [(victim_name, cpu, priority), ...]} with 2-cpu
    nodes; returns (nodes, bound_pods)."""
    nodes, pods_ = [], []
    for node_name, victims in specs.items():
        nodes.append(node(node_name, cpu="2", pods="16"))
        for name, cpu, prio in victims:
            pods_.append(pod(name, cpu=cpu, priority=prio,
                             node_name=node_name))
    return nodes, pods_


def nominate(nodes, pods_, preemptor):
    """Run both implementations; return per-impl (nominated_node,
    victims, final_status of the LAST record)."""
    want, got = run_both(nodes, pods_ + [preemptor], preempt_config())
    out = {}
    key = ("default", preemptor["metadata"]["name"])
    for impl, recs in (("oracle", want), ("engine", got)):
        first = recs[key][0]
        last = recs[key][-1]
        out[impl] = (first.nominated_node, sorted(first.preemption_victims),
                     last.status, last.selected_node)
    return out


class TestPreemptionVectors:
    def test_single_candidate_evicts_lone_victim(self):
        nodes, bound = preempt_cluster({"n0": [("low", "1800m", 1)]})
        res = nominate(nodes, bound,
                       pod("hi", cpu="1500m", priority=100))
        for impl, (nom, victims, status, sel) in res.items():
            assert nom == "n0", impl
            assert victims == ["default/low"], impl
            assert status == "Scheduled" and sel == "n0", impl

    def test_prefers_lowest_highest_victim_priority(self):
        # upstream TestSelectBestCandidate: minimize the highest victim
        # priority first
        nodes, bound = preempt_cluster({
            "n0": [("v10", "1800m", 10)],
            "n1": [("v50", "1800m", 50)],
        })
        res = nominate(nodes, bound, pod("hi", cpu="1500m", priority=100))
        for impl, (nom, victims, *_status) in res.items():
            assert nom == "n0", (impl, nom)
            assert victims == ["default/v10"], impl

    def test_equal_highest_prefers_smaller_priority_sum(self):
        nodes, bound = preempt_cluster({
            "n0": [("a1", "900m", 10), ("a2", "900m", 10)],
            "n1": [("b1", "1800m", 10)],
        })
        res = nominate(nodes, bound, pod("hi", cpu="1500m", priority=100))
        for impl, (nom, victims, *_status) in res.items():
            # both nodes need ALL their lower-prio pods evicted; highest
            # is 10 on both; sums 20 vs 10 -> n1
            assert nom == "n1", (impl, nom)
            assert victims == ["default/b1"], impl

    def test_equal_highest_and_sum_prefers_fewer_victims(self):
        nodes, bound = preempt_cluster({
            "n0": [("a1", "600m", 6), ("a2", "600m", 3), ("a3", "600m", 3)],
            "n1": [("b1", "900m", 6), ("b2", "900m", 6)],
        })
        res = nominate(nodes, bound, pod("hi", cpu="1500m", priority=100))
        for impl, (nom, victims, *_status) in res.items():
            # n0 must evict all three (sum 12, high 6); n1 both (sum 12,
            # high 6); counts 3 vs 2 -> n1
            assert nom == "n1", (impl, nom)
            assert victims == ["default/b1", "default/b2"], impl

    def test_equal_priority_pods_are_not_victims(self):
        nodes, bound = preempt_cluster({"n0": [("peer", "1800m", 100)]})
        res = nominate(nodes, bound, pod("hi", cpu="1500m", priority=100))
        for impl, (nom, victims, status, sel) in res.items():
            assert nom == "" and victims == [], (impl, nom)
            assert status == "Unschedulable", impl

    def test_reprieve_keeps_low_priority_pod_that_still_fits(self):
        # upstream selectVictimsOnNode: remove all lower-priority pods,
        # then reprieve in DESCENDING priority order whatever still
        # fits. 2-cpu node, preemptor 1500m: high-prio victim (1500m)
        # cannot be reprieved, low-prio (500m) can -> the HIGHER
        # priority pod is the victim.
        nodes, bound = preempt_cluster({
            "n0": [("lowA", "500m", 1), ("lowB", "1500m", 5)],
        })
        res = nominate(nodes, bound, pod("hi", cpu="1500m", priority=100))
        for impl, (nom, victims, status, sel) in res.items():
            assert nom == "n0", impl
            assert victims == ["default/lowB"], (impl, victims)
            assert status == "Scheduled" and sel == "n0", impl

    def test_multiple_victims_when_needed(self):
        nodes, bound = preempt_cluster({
            "n0": [("v1", "900m", 1), ("v2", "900m", 2)],
        })
        res = nominate(nodes, bound, pod("hi", cpu="1900m", priority=100))
        for impl, (nom, victims, *_status) in res.items():
            assert nom == "n0", impl
            assert victims == ["default/v1", "default/v2"], (impl, victims)

    def test_negative_priority_victims_evictable(self):
        nodes, bound = preempt_cluster({"n0": [("neg", "1800m", -10)]})
        res = nominate(nodes, bound, pod("zero", cpu="1500m", priority=0))
        for impl, (nom, victims, status, sel) in res.items():
            assert nom == "n0" and victims == ["default/neg"], impl
            assert status == "Scheduled", impl

    def test_no_preemption_when_feasible_without(self):
        nodes, bound = preempt_cluster({
            "n0": [("busy", "1800m", 1)],
            "n1": [],
        })
        want, got = run_both(nodes, bound + [
            pod("hi", cpu="1500m", priority=100)], preempt_config())
        for impl, recs in (("oracle", want), ("engine", got)):
            rec_list = recs[("default", "hi")]
            assert len(rec_list) == 1, impl  # no Nominated+retry pair
            assert rec_list[0].status == "Scheduled", impl
            assert rec_list[0].selected_node == "n1", impl

    def test_unschedulable_node_not_a_candidate(self):
        nodes, bound = preempt_cluster({"n0": [("low", "1800m", 1)]})
        nodes[0]["spec"]["unschedulable"] = True
        res = nominate(nodes, bound, pod("hi", cpu="1500m", priority=100))
        for impl, (nom, victims, status, sel) in res.items():
            assert nom == "" and status == "Unschedulable", (impl, nom)

    def test_preemption_would_not_help(self):
        # even with every lower-priority pod gone the pod cannot fit
        nodes, bound = preempt_cluster({"n0": [("low", "500m", 1)]})
        res = nominate(nodes, bound, pod("huge", cpu="3000m", priority=100))
        for impl, (nom, victims, status, sel) in res.items():
            assert nom == "" and status == "Unschedulable", (impl, nom)

    def test_victims_only_from_candidate_node(self):
        # preemption is per-node: a candidate's victim set never pools
        # pods from other nodes. Both nodes are symmetric candidates
        # (evicting the local 900m victim frees the full 2 cpu); the
        # upstream ranking criteria tie, so the exact winner is a
        # tie-break detail — pin only the per-node victim shape and that
        # both implementations break the tie identically.
        nodes, bound = preempt_cluster({
            "n0": [("x1", "900m", 1)],
            "n1": [("y1", "900m", 1)],
        })
        res = nominate(nodes, bound, pod("hi", cpu="1900m", priority=100))
        local = {"n0": ["default/x1"], "n1": ["default/y1"]}
        for impl, (nom, victims, status, sel) in res.items():
            assert nom in ("n0", "n1"), impl
            assert victims == local[nom], (impl, victims)
            assert status == "Scheduled" and sel == nom, impl
        assert res["oracle"][0] == res["engine"][0]

    def test_retry_failure_keeps_evictions_and_reports(self):
        # nominated, victims evicted, but a peer took the room first:
        # covered at engine level by parity tests; here pin the
        # two-record stream shape on a clean success instead
        nodes, bound = preempt_cluster({"n0": [("low", "1800m", 1)]})
        want, got = run_both(nodes, bound + [
            pod("hi", cpu="1500m", priority=100)], preempt_config())
        for impl, recs in (("oracle", want), ("engine", got)):
            rec_list = recs[("default", "hi")]
            assert [r.status for r in rec_list] == [
                "Nominated", "Scheduled"], impl
            assert rec_list[0].nominated_node == "n0", impl


# ---------------------------------------------------------------------------
# TaintToleration (upstream
# pkg/scheduler/framework/plugins/tainttoleration/taint_toleration_test.go
# TestTaintTolerationFilter)
# ---------------------------------------------------------------------------


def taint_config():
    from test_engine_parity import restricted_config

    return restricted_config(
        filters=("NodeUnschedulable", "NodeResourcesFit", "TaintToleration"),
    )


def tnode(name, taints=None):
    return node(name, cpu="8", taints=taints)


NO_SCHED = [{"key": "dedicated", "value": "user1", "effect": "NoSchedule"}]
PREFER = [{"key": "dedicated", "value": "user1", "effect": "PreferNoSchedule"}]


class TestTaintTolerationVectors:
    PLUGIN = "TaintToleration"

    def _nodes(self, taints):
        return [tnode("n-tainted", taints), tnode("n-clean")]

    def test_no_tolerations_cannot_schedule_on_tainted(self):
        # upstream "A pod having no tolerations can't be scheduled onto
        # a node with nonempty taints"
        assert_filter_vector(
            self._nodes(NO_SCHED), [pod("t")], taint_config(), "t",
            {"n-clean"}, self.PLUGIN)

    def test_matching_equal_toleration_schedules(self):
        # upstream "A pod which can be scheduled on a dedicated node
        # assigned to user1 with effect NoSchedule"
        tol = [{"key": "dedicated", "operator": "Equal", "value": "user1",
                "effect": "NoSchedule"}]
        assert_filter_vector(
            self._nodes(NO_SCHED), [pod("t", tolerations=tol)],
            taint_config(), "t", {"n-tainted", "n-clean"}, self.PLUGIN)

    def test_unmatched_value_filters(self):
        # upstream "A pod which can't be scheduled due to unmatched value"
        tol = [{"key": "dedicated", "operator": "Equal", "value": "user2",
                "effect": "NoSchedule"}]
        assert_filter_vector(
            self._nodes(NO_SCHED), [pod("t", tolerations=tol)],
            taint_config(), "t", {"n-clean"}, self.PLUGIN)

    def test_exists_operator_ignores_value(self):
        # upstream: operator Exists tolerates any value of the key
        tol = [{"key": "dedicated", "operator": "Exists",
                "effect": "NoSchedule"}]
        assert_filter_vector(
            self._nodes(NO_SCHED), [pod("t", tolerations=tol)],
            taint_config(), "t", {"n-tainted", "n-clean"}, self.PLUGIN)

    def test_empty_key_exists_tolerates_everything(self):
        # upstream toleration semantics: empty key + Exists matches all
        tol = [{"operator": "Exists"}]
        assert_filter_vector(
            self._nodes(NO_SCHED), [pod("t", tolerations=tol)],
            taint_config(), "t", {"n-tainted", "n-clean"}, self.PLUGIN)

    def test_prefer_no_schedule_never_filters(self):
        # upstream "A pod can be scheduled onto the node whose taints'
        # effect is PreferNoSchedule" — filtering ignores soft taints
        assert_filter_vector(
            self._nodes(PREFER), [pod("t")], taint_config(), "t",
            {"n-tainted", "n-clean"}, self.PLUGIN)

    def test_effect_mismatch_does_not_tolerate(self):
        # a NoExecute toleration does not tolerate a NoSchedule taint
        tol = [{"key": "dedicated", "operator": "Exists",
                "effect": "NoExecute"}]
        assert_filter_vector(
            self._nodes(NO_SCHED), [pod("t", tolerations=tol)],
            taint_config(), "t", {"n-clean"}, self.PLUGIN)


# ---------------------------------------------------------------------------
# NodeAffinity (upstream
# pkg/scheduler/framework/plugins/nodeaffinity/node_affinity_test.go
# TestNodeAffinity)
# ---------------------------------------------------------------------------


def na_config():
    from test_engine_parity import restricted_config

    return restricted_config(
        filters=("NodeUnschedulable", "NodeResourcesFit", "NodeAffinity"),
    )


def req_affinity(terms):
    return {
        "nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": terms
            }
        }
    }


def lnode(name, **labels):
    return node(name, cpu="8", labels=labels)


class TestNodeAffinityVectors:
    PLUGIN = "NodeAffinity"

    def _nodes(self):
        return [
            lnode("n1", foo="bar", gpu="2"),
            lnode("n2", foo="baz", gpu="6"),
            lnode("n3"),
        ]

    def test_in_operator(self):
        # upstream "Pod with matchExpressions using In operator"
        aff = req_affinity([{"matchExpressions": [
            {"key": "foo", "operator": "In", "values": ["bar"]}]}])
        assert_filter_vector(
            self._nodes(), [pod("t", affinity=aff)], na_config(), "t",
            {"n1"}, self.PLUGIN)

    def test_not_in_excludes_missing_label_passes(self):
        # upstream NotIn: nodes WITHOUT the label also pass
        aff = req_affinity([{"matchExpressions": [
            {"key": "foo", "operator": "NotIn", "values": ["bar"]}]}])
        assert_filter_vector(
            self._nodes(), [pod("t", affinity=aff)], na_config(), "t",
            {"n2", "n3"}, self.PLUGIN)

    def test_exists_and_does_not_exist(self):
        aff = req_affinity([{"matchExpressions": [
            {"key": "foo", "operator": "Exists"}]}])
        assert_filter_vector(
            self._nodes(), [pod("t", affinity=aff)], na_config(), "t",
            {"n1", "n2"}, self.PLUGIN)
        aff2 = req_affinity([{"matchExpressions": [
            {"key": "foo", "operator": "DoesNotExist"}]}])
        assert_filter_vector(
            self._nodes(), [pod("t2", affinity=aff2)], na_config(), "t2",
            {"n3"}, self.PLUGIN)

    def test_gt_lt_numeric(self):
        # upstream Gt/Lt parse label values as integers
        aff = req_affinity([{"matchExpressions": [
            {"key": "gpu", "operator": "Gt", "values": ["3"]}]}])
        assert_filter_vector(
            self._nodes(), [pod("t", affinity=aff)], na_config(), "t",
            {"n2"}, self.PLUGIN)
        aff2 = req_affinity([{"matchExpressions": [
            {"key": "gpu", "operator": "Lt", "values": ["3"]}]}])
        assert_filter_vector(
            self._nodes(), [pod("t2", affinity=aff2)], na_config(), "t2",
            {"n1"}, self.PLUGIN)

    def test_terms_are_ored_expressions_are_anded(self):
        # upstream: nodeSelectorTerms OR; matchExpressions within AND
        aff = req_affinity([
            {"matchExpressions": [
                {"key": "foo", "operator": "In", "values": ["bar"]},
                {"key": "gpu", "operator": "Gt", "values": ["1"]}]},
            {"matchExpressions": [
                {"key": "foo", "operator": "In", "values": ["baz"]}]},
        ])
        assert_filter_vector(
            self._nodes(), [pod("t", affinity=aff)], na_config(), "t",
            {"n1", "n2"}, self.PLUGIN)
        # AND failure: foo=bar but gpu not > 3
        aff2 = req_affinity([{"matchExpressions": [
            {"key": "foo", "operator": "In", "values": ["bar"]},
            {"key": "gpu", "operator": "Gt", "values": ["3"]}]}])
        assert_filter_vector(
            self._nodes(), [pod("t2", affinity=aff2)], na_config(), "t2",
            set(), self.PLUGIN)

    def test_no_matching_term_unschedulable(self):
        aff = req_affinity([{"matchExpressions": [
            {"key": "foo", "operator": "In", "values": ["nope"]}]}])
        assert_filter_vector(
            self._nodes(), [pod("t", affinity=aff)], na_config(), "t",
            set(), self.PLUGIN)


def test_no_execute_taint_filters_too():
    # upstream DoNotScheduleTaintsFilterFunc: NoSchedule AND NoExecute
    # both filter at scheduling time
    taints = [{"key": "evict", "value": "now", "effect": "NoExecute"}]
    assert_filter_vector(
        [tnode("n-tainted", taints), tnode("n-clean")], [pod("t")],
        taint_config(), "t", {"n-clean"}, "TaintToleration")


def test_unschedulable_node_tolerated():
    # upstream NodeUnschedulable plugin: spec.unschedulable acts as the
    # node.kubernetes.io/unschedulable:NoSchedule taint, and a pod
    # TOLERATING it schedules there (plugins.NodeUnschedulable
    # TestNodeUnschedulable "unschedulable node + tolerated pod")
    def mk():
        ns = [tnode("n-off"), tnode("n-on")]
        ns[0]["spec"] = {"unschedulable": True}
        return ns

    tol = [{"key": "node.kubernetes.io/unschedulable",
            "operator": "Exists", "effect": "NoSchedule"}]
    assert_filter_vector(
        mk(), [pod("t", tolerations=tol)], taint_config(), "t",
        {"n-off", "n-on"}, "NodeUnschedulable")
    assert_filter_vector(
        mk(), [pod("t2")], taint_config(), "t2",
        {"n-on"}, "NodeUnschedulable")


# ---------------------------------------------------------------------------
# Scoring vectors: NodeResourcesFit (LeastAllocated) and
# NodeResourcesBalancedAllocation (upstream
# pkg/scheduler/framework/plugins/noderesources/least_allocated_test.go and
# balanced_allocation_test.go) — expected RAW scores computed by hand from
# the upstream formulas, never from either implementation:
#   LeastAllocated = sum_r[ (alloc_r - req_r) * 100 / alloc_r * w_r ]
#                    / sum(w_r)          (integer division per upstream)
#   Balanced       = (1 - std({req_r/alloc_r})) * 100, rounded down
# ---------------------------------------------------------------------------


def score_table(rec):
    raw = rec.to_annotations()["scheduler-simulator/score-result"]
    return json.loads(raw) if raw else {}


def assert_score_vector(nodes, pods_, cfg, test_pod, plugin, expect):
    want, got = run_both(nodes, pods_, cfg)
    for impl, recs in (("oracle", want), ("engine", got)):
        rec = recs[("default", test_pod)][-1]
        table = score_table(rec)
        scores = {
            n: int(plugins[plugin])
            for n, plugins in table.items()
            if plugin in plugins
        }
        assert scores == expect, (impl, plugin, scores, expect)


class TestResourceScoreVectors:
    def _cfg(self):
        from test_engine_parity import restricted_config

        return restricted_config()

    def _nodes(self):
        # n1: 8 cpu / 16Gi; n2: 4 cpu / 16Gi — chosen so every upstream
        # formula lands on exact integers or known truncations
        return [
            node("n1", cpu="8", mem="16Gi"),
            node("n2", cpu="4", mem="16Gi"),
        ]

    def test_least_allocated_empty_nodes(self):
        # upstream least_allocated_test.go "nothing scheduled, resources
        # requested" family: pod 2cpu/4Gi →
        #   n1: ((8-2)*100/8 + (16-4)*100/16) / 2 = (75 + 75) / 2 = 75
        #   n2: ((4-2)*100/4 + 75) / 2 = (50 + 75) / 2 = 62 (truncated)
        assert_score_vector(
            self._nodes(), [pod("t", cpu="2", mem="4Gi")], self._cfg(),
            "t", "NodeResourcesFit", {"n1": 75, "n2": 62})

    def test_balanced_allocation_empty_nodes(self):
        # upstream balanced_allocation_test.go: fractions cpu/mem →
        #   n1: 0.25 vs 0.25 → std 0 → 100
        #   n2: 0.50 vs 0.25 → std |0.5-0.25|/2 = 0.125 → 87 (truncated)
        assert_score_vector(
            self._nodes(), [pod("t", cpu="2", mem="4Gi")], self._cfg(),
            "t", "NodeResourcesBalancedAllocation", {"n1": 100, "n2": 87})

    def test_least_allocated_counts_existing_pods(self):
        # existing pod on n1 consumes 4cpu/4Gi: requested totals include
        # it (upstream "resources requested, pods scheduled with
        # resources"):
        #   n1: ((8-4-2)*100/8 + (16-4-4)*100/16) / 2 = (25 + 50) / 2 = 37
        #   n2 unchanged: 62
        existing = pod("e", cpu="4", mem="4Gi", node_name="n1")
        assert_score_vector(
            self._nodes(), [existing, pod("t", cpu="2", mem="4Gi")],
            self._cfg(), "t", "NodeResourcesFit", {"n1": 37, "n2": 62})
