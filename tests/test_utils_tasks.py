"""Retry + bounded map (reference util semantics)."""

import pytest

from kube_scheduler_simulator_tpu.utils.tasks import (
    RetryError,
    bounded_map,
    retry,
)


def test_retry_succeeds_after_failures():
    calls = {"n": 0}
    delays = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert retry(flaky, sleep=delays.append) == "ok"
    assert calls["n"] == 3
    # exponential: 100ms, then 300ms (retry.go 100ms x 3^n)
    assert delays == pytest.approx([0.1, 0.3])


def test_retry_exhausts():
    delays = []
    with pytest.raises(RetryError) as ei:
        retry(lambda: 1 / 0, steps=3, sleep=delays.append)
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, ZeroDivisionError)
    assert len(delays) == 2  # no sleep after the final attempt


def test_retry_non_retryable_raises_immediately():
    calls = {"n": 0}

    def bad():
        calls["n"] += 1
        raise ValueError("fatal")

    with pytest.raises(ValueError):
        retry(bad, retryable=lambda e: isinstance(e, OSError),
              sleep=lambda _: None)
    assert calls["n"] == 1


def test_bounded_map_order_and_error():
    assert bounded_map(lambda x: x * x, list(range(20)), max_workers=4) == [
        x * x for x in range(20)
    ]

    def boom(x):
        if x == 3:
            raise RuntimeError("x=3")
        return x

    with pytest.raises(RuntimeError):
        bounded_map(boom, list(range(6)), max_workers=2)
    assert bounded_map(lambda x: x, []) == []
