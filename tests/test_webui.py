"""The built-in dashboard page: served, self-contained, API-consistent."""

import urllib.request

from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import SimulatorService


def test_dashboard_served_and_references_live_routes():
    server = SimulatorServer(SimulatorService(), port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        for path in ("/", "/ui"):
            with urllib.request.urlopen(base + path) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/html")
                html = resp.read().decode()
        # every API route the page drives must appear in the page AND
        # exist on this server (GET-able ones fetched to prove it)
        for route in ("/api/v1/schedulerconfiguration", "/api/v1/export"):
            assert route in html
            with urllib.request.urlopen(base + route) as resp:
                assert resp.status == 200
        for route in (
            "/api/v1/listwatchresources",
            "/api/v1/schedule",
            "/api/v1/schedule?mode=gang",
            "/api/v1/reset",
        ):
            assert route in html
        assert "scheduler-simulator/" in html  # annotation inspection
    finally:
        server.shutdown()
