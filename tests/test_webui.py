"""The built-in dashboard page: served, self-contained, API-consistent,
and the authoring workflow it drives (YAML create/edit/delete + creation
templates, reference web/components/lib/templates/*.yaml) actually works
end-to-end against the serving surface."""

import json
import urllib.error
import urllib.request

import pytest

from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
from kube_scheduler_simulator_tpu.server.service import SimulatorService
from kube_scheduler_simulator_tpu.server.webui import PAGE, TEMPLATES


def _req(url, data=None, method="GET", ctype="application/json"):
    req = urllib.request.Request(
        url,
        data=data if isinstance(data, (bytes, type(None))) else data.encode(),
        method=method,
        headers={"Content-Type": ctype},
    )
    with urllib.request.urlopen(req) as resp:
        body = resp.read()
        return resp.status, body


def test_dashboard_served_and_references_live_routes():
    server = SimulatorServer(SimulatorService(), port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        for path in ("/", "/ui"):
            with urllib.request.urlopen(base + path) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/html")
                html = resp.read().decode()
        # every API route the page drives must appear in the page AND
        # exist on this server (GET-able ones fetched to prove it)
        for route in ("/api/v1/schedulerconfiguration", "/api/v1/export"):
            assert route in html
            with urllib.request.urlopen(base + route) as resp:
                assert resp.status == 200
        for route in (
            "/api/v1/listwatchresources",
            "/api/v1/schedule",
            "/api/v1/schedule?mode=gang",
            "/api/v1/reset",
            "/api/v1/import",
        ):
            assert route in html
        assert "scheduler-simulator/" in html  # annotation inspection
    finally:
        server.shutdown()


def test_page_covers_all_seven_kinds_and_templates():
    # the tab spec and the embedded creation templates must cover the
    # reference UI's seven kinds (ResourcesViewPanel.vue + templates/)
    kinds = (
        "nodes", "pods", "pvs", "pvcs",
        "storageclasses", "priorityclasses", "namespaces",
    )
    assert set(TEMPLATES) == set(kinds)
    for k in kinds:
        assert f"'{k}'" in PAGE or f'"{k}"' in PAGE
        assert "generateName" in TEMPLATES[k]
    # wire names for the watch stream
    for wire in (
        "persistentvolumes", "persistentvolumeclaims",
        "storageclasses", "priorityclasses", "namespaces",
    ):
        assert wire in PAGE
    # the authoring verbs the page drives
    for probe in ("format=yaml", "DELETE", "podsByNode"):
        assert probe in PAGE


class TestAuthoringWorkflow:
    """The reference demo loop, driven exactly as the page's JS does:
    create node + pod from the creation templates (YAML bodies),
    schedule, inspect the per-plugin table, edit, delete."""

    def setup_method(self):
        self.server = SimulatorServer(SimulatorService(), port=0).start()
        self.base = f"http://127.0.0.1:{self.server.port}"

    def teardown_method(self):
        self.server.shutdown()

    def test_create_from_templates_schedule_inspect_edit_delete(self):
        base = self.base
        # 1) create a node and a pod from the embedded templates (YAML)
        st, body = _req(
            f"{base}/api/v1/resources/nodes",
            data=TEMPLATES["nodes"],
            method="POST",
            ctype="application/yaml",
        )
        assert st == 201
        node_name = json.loads(body)["metadata"]["name"]
        assert node_name.startswith("node-") and len(node_name) > len("node-")
        st, body = _req(
            f"{base}/api/v1/resources/pods",
            data=TEMPLATES["pods"],
            method="POST",
            ctype="application/yaml",
        )
        assert st == 201
        pod_name = json.loads(body)["metadata"]["name"]
        # 2) schedule, then the pod must be bound and carry the
        # per-plugin result annotations the detail panel renders
        _req(f"{base}/api/v1/schedule", data=b"", method="POST")
        st, body = _req(f"{base}/api/v1/resources/pods/default/{pod_name}")
        pod = json.loads(body)
        assert pod["spec"]["nodeName"] == node_name
        ann = pod["metadata"]["annotations"]
        assert "scheduler-simulator/filter-result" in ann
        assert "scheduler-simulator/score-result" in ann
        # 3) the editor loads the object as YAML
        st, body = _req(
            f"{base}/api/v1/resources/pods/default/{pod_name}?format=yaml"
        )
        assert st == 200
        yaml_text = body.decode()
        assert yaml_text.startswith("metadata:") or "metadata:" in yaml_text
        assert "nodeName" in yaml_text
        # 4) edit: the editor saves via item-path PUT (replace): added
        # fields land AND removed fields actually disappear
        import yaml as _yaml

        obj = _yaml.safe_load(yaml_text)
        obj["metadata"].setdefault("labels", {})["edited"] = "yes"
        removed_ann = "scheduler-simulator/score-result"
        del obj["metadata"]["annotations"][removed_ann]
        st, _ = _req(
            f"{base}/api/v1/resources/pods/default/{pod_name}",
            data=_yaml.safe_dump(obj),
            method="PUT",
            ctype="application/yaml",
        )
        assert st == 200
        st, body = _req(f"{base}/api/v1/resources/pods/default/{pod_name}")
        edited = json.loads(body)
        assert edited["metadata"]["labels"]["edited"] == "yes"
        assert removed_ann not in edited["metadata"]["annotations"]
        # PUT with a mismatched body name is rejected
        bad = dict(obj)
        bad["metadata"] = dict(obj["metadata"], name="other-name")
        try:
            _req(
                f"{base}/api/v1/resources/pods/default/{pod_name}",
                data=_yaml.safe_dump(bad),
                method="PUT",
                ctype="application/yaml",
            )
            raise AssertionError("mismatched name accepted")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        # 5) delete through the row action's route
        st, _ = _req(
            f"{base}/api/v1/resources/pods/default/{pod_name}",
            method="DELETE",
        )
        assert st == 200
        with pytest.raises(urllib.error.HTTPError):
            _req(f"{base}/api/v1/resources/pods/default/{pod_name}")

    def test_gang_schedule_inspect(self):
        """The page's 'Schedule (gang)' button: a gang run must leave
        the same per-plugin annotations the detail panel renders
        (VERDICT r4 #6 — gang mode used to emit no records)."""
        base = self.base
        _req(
            f"{base}/api/v1/resources/nodes",
            data=TEMPLATES["nodes"],
            method="POST",
            ctype="application/yaml",
        )
        st, body = _req(
            f"{base}/api/v1/resources/pods",
            data=TEMPLATES["pods"],
            method="POST",
            ctype="application/yaml",
        )
        pod_name = json.loads(body)["metadata"]["name"]
        st, body = _req(
            f"{base}/api/v1/schedule?mode=gang", data=b"", method="POST"
        )
        assert st == 200 and json.loads(body)["scheduled"] == 1
        st, body = _req(f"{base}/api/v1/resources/pods/default/{pod_name}")
        pod = json.loads(body)
        assert pod["spec"]["nodeName"]
        ann = pod["metadata"]["annotations"]
        assert "scheduler-simulator/filter-result" in ann
        assert "scheduler-simulator/score-result" in ann
        assert "scheduler-simulator/selected-node" in ann

    def test_all_templates_create_valid_objects(self):
        for kind in TEMPLATES:
            st, body = _req(
                f"{self.base}/api/v1/resources/{kind}",
                data=TEMPLATES[kind],
                method="POST",
                ctype="application/yaml",
            )
            assert st == 201, kind
            name = json.loads(body)["metadata"]["name"]
            assert name and not name.endswith("-"), (kind, name)

    def test_malformed_yaml_rejected_not_500_crash(self):
        st = None
        try:
            _req(
                f"{self.base}/api/v1/resources/pods",
                data=": not yaml : [",
                method="POST",
                ctype="application/yaml",
            )
        except urllib.error.HTTPError as e:
            st = e.code
        assert st == 500  # boundary-handled error, served as JSON message


def test_weight_editor_embedded_and_weight_config_applies():
    """The per-plugin score-weight editor (VERDICT r4 weak #6): the page
    embeds the v1.26 default score set for the editor seed, and the
    exact config shape the editor writes (.score disabled:* +
    enabled-with-weights) round-trips through the live config endpoint
    and changes the effective weights."""
    import json

    from kube_scheduler_simulator_tpu.sched.config import default_plugins

    score_defaults = default_plugins()["score"]
    for p in score_defaults:
        assert p["name"] in PAGE
    assert "applyWeights" in PAGE and "wtable" in PAGE
    server = SimulatorServer(SimulatorService(), port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        # what the editor's applyWeights() writes
        body = json.dumps({
            "profiles": [{
                "schedulerName": "default-scheduler",
                "plugins": {"score": {
                    "disabled": [{"name": "*"}],
                    "enabled": [
                        {"name": "NodeResourcesFit", "weight": 7},
                        {"name": "TaintToleration", "weight": 2},
                    ],
                }},
            }],
        }).encode()
        req = urllib.request.Request(
            base + "/api/v1/schedulerconfiguration", data=body, method="POST"
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status in (200, 202)
        with urllib.request.urlopen(
            base + "/api/v1/schedulerconfiguration"
        ) as resp:
            cfg = json.loads(resp.read())
        enabled = cfg["profiles"][0]["plugins"]["score"]["enabled"]
        assert {p["name"]: p["weight"] for p in enabled} == {
            "NodeResourcesFit": 7, "TaintToleration": 2,
        }
    finally:
        server.shutdown()
