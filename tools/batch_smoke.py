"""`make batch-smoke`: cross-tenant continuous batching end-to-end on
CPU (server/batchplane.py, docs/sessions.md). Four gates, one JSON line:

1. **One device dispatch per window** — N bucket-compatible sessions
   scheduling concurrently must be served by ONE `batch.seq.run`
   dispatch (program-ledger call count == windows executed == 1, window
   fill == N), with every tenant attributed on the one call.
2. **Per-session trace parity** — each tenant's full result-record set
   (status, placement, all 13 annotations) must be BYTE-IDENTICAL to a
   solo-dispatch run of the same cluster: batching may change
   throughput, never an answer.
3. **Lone-tenant fairness** — a single tenant's pass waits at most
   ~one `KSS_BATCH_WINDOW_MS` before the solo fallback serves it.
4. **Gang batching** — N tenants' gang passes (the fused device
   fixpoint, record=False) served by ONE `batch.gang.run` dispatch,
   every tenant attributed, placements + rounds identical to solo gang
   dispatch, and `soloFallbacks` NOT ticking (the old "gang passes are
   not batch-eligible" fallback is gone).

Exit 0 on pass. Small enough for CI (seconds, CPU-only): a sanity gate,
not a benchmark — the throughput curve lives in
`bench.py --concurrency-probe` (docs/performance.md).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("KSS_NO_SPECULATIVE_COMPILE", "1")
os.environ["KSS_PROGRAM_LEDGER"] = "1"

N = 4
WINDOW_MS = 150.0


def _node(name: str) -> dict:
    return {
        "metadata": {"name": name},
        "status": {
            "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
        },
    }


def _pod(name: str, cpu_m: int) -> dict:
    return {
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "resources": {
                        "requests": {"cpu": f"{cpu_m}m", "memory": "256Mi"}
                    },
                }
            ]
        },
    }


def _snapshot(i: int) -> dict:
    """Tenant i's cluster: identical shapes (one batch key), distinct
    request values (distinct placements)."""
    return {
        "nodes": [_node(f"n{j}") for j in range(4)],
        "pods": [_pod(f"p{j}", 100 + 100 * i + 50 * j) for j in range(6)],
    }


def _results_doc(results) -> str:
    return json.dumps(
        [
            {
                "ns": r.pod_namespace,
                "name": r.pod_name,
                "status": r.status,
                "node": r.selected_node,
                "ann": r.to_annotations(),
            }
            for r in results
        ],
        sort_keys=True,
    )


def main() -> int:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from kube_scheduler_simulator_tpu.server.batchplane import (
        BATCH_GANG_LABEL,
        BATCH_SEQ_LABEL,
        BatchPlane,
    )
    from kube_scheduler_simulator_tpu.server.service import SimulatorService
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager
    from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod

    report: dict = {"smoke": "batch", "sessions": N}
    failures: list[str] = []

    # -- solo baseline (plane off): the parity oracle ----------------------
    solo_mgr = SessionManager(
        SimulatorService(), max_sessions=16, max_concurrent_passes=N
    )
    solo_docs = {}
    for i in range(N):
        sess, errs = solo_mgr.create(name=f"solo{i}", snapshot=_snapshot(i))
        assert not errs, errs
        solo_docs[i] = _results_doc(sess.service.scheduler.schedule())
    solo_mgr.shutdown()

    # -- batched run -------------------------------------------------------
    ledger_mod.LEDGER.reset()
    mgr = SessionManager(
        SimulatorService(), max_sessions=16, max_concurrent_passes=N
    )
    plane = BatchPlane(
        window_ms=10_000.0,  # flushes when FULL: deterministic one-window
        max_sessions=N,
        metrics=mgr.get("default").service.scheduler.metrics,
    )
    mgr.batch_plane = plane
    mgr.get("default").service.scheduler.batch_plane = plane
    sessions = []
    for i in range(N):
        sess, errs = mgr.create(name=f"t{i}", snapshot=_snapshot(i))
        assert not errs, errs
        sessions.append(sess)
    out: dict = {}
    errors: dict = {}
    barrier = threading.Barrier(N)

    def run(i):
        try:
            barrier.wait(timeout=60)
            with mgr.pass_slot():
                out[i] = _results_doc(sessions[i].service.scheduler.schedule())
        except Exception as e:  # noqa: BLE001 — reported below
            errors[i] = repr(e)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    if errors:
        failures.append(f"batched passes raised: {errors}")

    # gate 1: one device dispatch per window, every tenant attributed
    default_snap = mgr.get("default").service.scheduler.metrics.snapshot()
    windows = default_snap["phases"]["batchWindows"]
    occupancy = default_snap["phases"]["batchOccupancySum"]
    report["batchWindows"] = windows
    report["batchOccupancySum"] = occupancy
    report["batchOccupancy"] = default_snap["batching"]["batchOccupancy"]
    if windows != 1 or occupancy != N:
        failures.append(
            f"expected ONE full window, got windows={windows} fill={occupancy}"
        )
    batch_recs = [
        rec
        for rec in ledger_mod.LEDGER.snapshot()["programs"]
        if rec["label"] == BATCH_SEQ_LABEL
    ]
    calls = sum(rec["calls"] for rec in batch_recs)
    attributed = {
        sid for rec in batch_recs for sid in rec["sessions"]
    }
    report["batchDispatches"] = calls
    report["attributedSessions"] = sorted(attributed)
    if calls != 1:
        failures.append(f"expected 1 ledger-pinned device dispatch, got {calls}")
    missing = {s.id for s in sessions} - attributed
    if missing:
        failures.append(f"sessions missing from ledger attribution: {missing}")

    # gate 2: per-session trace parity vs solo dispatch
    mismatches = [i for i in range(N) if out.get(i) != solo_docs[i]]
    report["parity"] = not mismatches
    if mismatches:
        failures.append(f"solo/batched result divergence for sessions {mismatches}")

    # gate 4 (gang): N tenants' gang passes (record=False) batch into
    # ONE `batch.gang.run` dispatch — the vmapped fused fixpoint — with
    # every tenant attributed on the one call and placements identical
    # to solo gang dispatch. soloFallbacks must NOT tick: the old
    # "gang passes are not batch-eligible" branch is gone.
    solo_gang_mgr = SessionManager(
        SimulatorService(), max_sessions=16, max_concurrent_passes=N
    )
    solo_gang = {}
    for i in range(N):
        sess, errs = solo_gang_mgr.create(
            name=f"gsolo{i}", snapshot=_snapshot(i)
        )
        assert not errs, errs
        placements, rounds, _ = sess.service.scheduler.schedule_gang(
            record=False
        )
        solo_gang[i] = (placements, rounds)
    solo_gang_mgr.shutdown()

    gang_sessions = []
    for i in range(N):
        sess, errs = mgr.create(name=f"g{i}", snapshot=_snapshot(i))
        assert not errs, errs
        gang_sessions.append(sess)
    gout: dict = {}
    gerrors: dict = {}
    gbarrier = threading.Barrier(N)

    def grun(i):
        try:
            gbarrier.wait(timeout=60)
            with mgr.pass_slot():
                placements, rounds, _ = (
                    gang_sessions[i].service.scheduler.schedule_gang(
                        record=False
                    )
                )
                gout[i] = (placements, rounds)
        except Exception as e:  # noqa: BLE001 — reported below
            gerrors[i] = repr(e)

    gthreads = [threading.Thread(target=grun, args=(i,)) for i in range(N)]
    for t in gthreads:
        t.start()
    for t in gthreads:
        t.join(timeout=600)
    if gerrors:
        failures.append(f"batched gang passes raised: {gerrors}")
    gang_recs = [
        rec
        for rec in ledger_mod.LEDGER.snapshot()["programs"]
        if rec["label"] == BATCH_GANG_LABEL
    ]
    gang_calls = sum(rec["calls"] for rec in gang_recs)
    gang_attributed = {sid for rec in gang_recs for sid in rec["sessions"]}
    report["gangBatchDispatches"] = gang_calls
    report["gangAttributedSessions"] = sorted(gang_attributed)
    if gang_calls != 1:
        failures.append(
            f"expected 1 ledger-pinned gang dispatch, got {gang_calls}"
        )
    gmissing = {s.id for s in gang_sessions} - gang_attributed
    if gmissing:
        failures.append(
            f"gang sessions missing from ledger attribution: {gmissing}"
        )
    gang_mismatch = [i for i in range(N) if gout.get(i) != solo_gang[i]]
    report["gangParity"] = not gang_mismatch
    if gang_mismatch:
        failures.append(
            f"solo/batched gang divergence for sessions {gang_mismatch}"
        )
    for i, s in enumerate(gang_sessions):
        ph = s.service.scheduler.metrics.snapshot()["phases"]
        if ph["batchedGangPasses"] != 1 or ph["soloFallbacks"] != 0:
            failures.append(
                f"gang session {i}: batchedGangPasses="
                f"{ph['batchedGangPasses']} soloFallbacks="
                f"{ph['soloFallbacks']} (want 1 / 0)"
            )

    # gate 3: a lone tenant is bounded by ~one window
    lone_mgr = SessionManager(
        SimulatorService(), max_sessions=4, max_concurrent_passes=2
    )
    lone_plane = BatchPlane(
        window_ms=WINDOW_MS,
        max_sessions=N,
        metrics=lone_mgr.get("default").service.scheduler.metrics,
    )
    lone_mgr.batch_plane = lone_plane
    lone_mgr.get("default").service.scheduler.batch_plane = lone_plane
    lone, errs = lone_mgr.create(name="lone", snapshot=_snapshot(0))
    assert not errs, errs
    lone.service.scheduler.schedule()  # warm-up pays window + solo compile
    for p in _snapshot(0)["pods"]:
        lone.service.store.delete("pods", p["metadata"]["name"], "default")
    lone.service.import_({"pods": _snapshot(0)["pods"]})
    t0 = time.monotonic()
    lone.service.scheduler.schedule()
    lone_wait_s = time.monotonic() - t0
    report["loneTenantPassSeconds"] = round(lone_wait_s, 4)
    report["loneTenantBoundSeconds"] = round(WINDOW_MS / 1000.0 + 2.0, 4)
    if lone_wait_s > WINDOW_MS / 1000.0 + 2.0:
        failures.append(
            f"lone tenant waited {lone_wait_s:.2f}s "
            f"(window {WINDOW_MS}ms + 2s CPU slack)"
        )
    lone_mgr.shutdown()
    mgr.shutdown()

    report["ok"] = not failures
    if failures:
        report["failures"] = failures
    print(json.dumps(report))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
