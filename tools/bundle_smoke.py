"""`make bundle-smoke`: the AOT-bundle cross-process reuse gate.

The persistent bundle store's whole point (utils/bundles.py,
docs/performance.md) is that a COLD PROCESS never re-compiles an engine
program another process already compiled. This smoke proves exactly
that, end to end, on CPU:

1. Run the cold-start probe workload (the serving path's
   `schedule_gang` over a small synthetic cluster) in a FRESH
   subprocess with `KSS_AOT_BUNDLES=1` against an empty bundle dir and
   an empty XLA compile-cache dir: the run compiles, SAVES bundles
   (`bundleSaves >= 1`), and reports its placements digest.

2. Run the identical workload in a SECOND fresh subprocess sharing the
   now-warm bundle dir: every engine program must resolve from the
   store — `bundleMisses == 0` (zero program compiles: a miss is
   precisely "an engine program had to be compiled") and
   `bundleLoads >= 1` — with a byte-identical placements digest.

Exit 0 on pass, 1 with the problem list otherwise; one JSON line either
way. Small enough for tier-1-adjacent use (seconds, CPU-only); the
measured ≥5x time-to-first-scheduled-pod gate lives in
`python bench.py` (`coldStartBundled`).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the child workload: the cold-start probe's serving-path pass, plus
# the bundle-store accounting the parent asserts on. Kept inline so the
# smoke has exactly one moving part.
_CHILD = """
import json

from kube_scheduler_simulator_tpu.models.store import ResourceStore
from kube_scheduler_simulator_tpu.server.service import SchedulerService
from kube_scheduler_simulator_tpu.utils import bundles

store = ResourceStore()
for i in range(8):
    store.apply(
        "nodes",
        {
            "metadata": {"name": f"bn{i}"},
            "status": {
                "allocatable": {"cpu": "64", "memory": "128Gi", "pods": "110"}
            },
        },
    )
for i in range(32):
    store.apply(
        "pods",
        {
            "metadata": {"name": f"bp{i}"},
            "spec": {
                "containers": [
                    {
                        "name": "c",
                        "resources": {
                            "requests": {"cpu": "250m", "memory": "256Mi"}
                        },
                    }
                ]
            },
        },
    )
svc = SchedulerService(store)
placements, _, _ = svc.schedule_gang(record=False)
bundles.STORE.flush(60.0)
print(
    json.dumps(
        {
            "placements": sorted(
                [ns, name, node] for (ns, name), node in placements.items()
            ),
            "bundles": bundles.STORE.stats(),
            "compile": {
                "compileMisses": svc.broker.compile_misses,
                "compileHits": svc.broker.compile_hits,
            },
        }
    )
)
"""


def _run_child(env: dict) -> dict:
    out = subprocess.run(
        [sys.executable, "-c", _CHILD],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO_ROOT,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"child exited {out.returncode}:\n{out.stdout}\n{out.stderr}"
        )
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and "bundles" in doc:
            return doc
    raise RuntimeError(f"child emitted no result line:\n{out.stdout}")


def main() -> int:
    problems: list[str] = []
    bundle_dir = tempfile.mkdtemp(prefix="kss-bundle-smoke-")
    cache_dir = tempfile.mkdtemp(prefix="kss-bundle-smoke-cache-")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KSS_AOT_BUNDLES="1",
        KSS_BUNDLE_DIR=bundle_dir,
        KSS_JAX_CACHE_DIR=cache_dir,
        # a deterministic program set: no background speculative builds
        # racing the exit flush
        KSS_NO_SPECULATIVE_COMPILE="1",
    )

    first = _run_child(env)
    second = _run_child(env)

    f_stats, s_stats = first["bundles"], second["bundles"]
    if f_stats["bundleSaves"] < 1:
        problems.append(
            f"first process saved no bundles: {f_stats}"
        )
    # "compileMisses == 0 for engine programs": a bundle-store MISS is
    # exactly "an engine program had to be compiled" — the second
    # process must have none (the broker's engine-level compileMisses
    # stays 1 per process: the warm-engine MAP is per-process; what the
    # bundles eliminate is the program compile inside that build)
    if s_stats["bundleMisses"] != 0:
        problems.append(
            f"second process compiled engine programs: {s_stats}"
        )
    if s_stats["bundleLoads"] < 1:
        problems.append(
            f"second process loaded no bundles: {s_stats}"
        )
    if s_stats["bundleBypasses"] != 0:
        problems.append(
            f"second process bypassed bundles: {s_stats}"
        )
    if first["placements"] != second["placements"]:
        problems.append("bundled placements diverged from the compiled run")
    if not first["placements"]:
        problems.append("workload scheduled nothing — the gate proved nothing")

    line = {
        "ok": not problems,
        "firstProcess": {
            "bundles": f_stats,
            "compile": first["compile"],
        },
        "secondProcess": {
            "bundles": s_stats,
            "compile": second["compile"],
        },
        "placementsIdentical": first["placements"] == second["placements"],
        "problems": problems,
    }
    print(json.dumps(line), flush=True)
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
