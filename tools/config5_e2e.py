"""BASELINE config #5 end-to-end: 50k pods x 5k nodes, mixed priorities.

The one BASELINE row that never had a scheduling number (VERDICT r4
missing #4: import + encode were timed in round 4, the scheduling pass
never ran at this shape on any backend). This script runs the WHOLE
path the way a user would: snapshot import into the store -> list back
out -> encode -> gang fixpoint (full default plugin set incl.
DefaultPreemption) -> placement count, printing one JSON line per phase
and a final summary line.

Run on whatever backend is alive (the driver's axon chip, else the CPU
fallback the caller sets up):

    python tools/config5_e2e.py [--nodes 5000 --pods 50000]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=50000)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument(
        "--window", type=int, default=0,
        help="eval_window (0 = off): queue-prefix eval bounding — the"
        " round-5 eval-dominance lever; cuts per-round evaluation from"
        " all-pending to a queue prefix (see GangScheduler)",
    )
    args = ap.parse_args()

    # persistent compile cache: a killed/retried run at this shape must
    # not repay the (many-minute, host-CPU-bound) compile
    from kube_scheduler_simulator_tpu.utils.compilecache import (
        enable_compile_cache,
    )

    enable_compile_cache()

    def phase(name, t0):
        dt = time.perf_counter() - t0
        print(json.dumps({"phase": name, "seconds": round(dt, 2)}), flush=True)
        return dt

    from kube_scheduler_simulator_tpu.engine import TPU32, encode_cluster
    from kube_scheduler_simulator_tpu.engine.engine import supported_config
    from kube_scheduler_simulator_tpu.engine.gang import GangScheduler
    from kube_scheduler_simulator_tpu.models.snapshot import (
        export_snapshot,
        import_snapshot,
    )
    from kube_scheduler_simulator_tpu.models.store import ResourceStore
    from kube_scheduler_simulator_tpu.synth import synthetic_cluster

    import numpy as np

    t0 = time.perf_counter()
    nodes, pods = synthetic_cluster(
        args.nodes, args.pods, seed=args.seed, priorities=True
    )
    t_synth = phase("synth", t0)

    # import the manifests through the snapshot path (the reference's
    # one-shot cluster import, simulator/docs export/import API)
    t0 = time.perf_counter()
    src = ResourceStore()
    for n in nodes:
        src.apply("nodes", n)
    for p in pods:
        src.apply("pods", p)
    snap = export_snapshot(src, None)
    store = ResourceStore()
    import_snapshot(store, snap)
    t_import = phase("import", t0)

    t0 = time.perf_counter()
    enc = encode_cluster(
        store.list("nodes"),
        store.list("pods"),
        supported_config(),
        policy=TPU32,
    )
    t_encode = phase("encode", t0)

    t0 = time.perf_counter()
    gang = GangScheduler(
        enc, chunk=args.chunk, eval_window=args.window or None
    )
    state, rounds = gang.run()
    placed = int((np.asarray(state.assignment) >= 0).sum())
    t_sched = phase("gang_schedule", t0)

    import jax

    print(
        json.dumps(
            {
                "config5_dps": round(args.pods / t_sched, 1),
                "shape": f"{args.pods}x{args.nodes}",
                **({"window": args.window} if args.window else {}),
                "rounds": int(np.asarray(rounds)),
                "placed": placed,
                "pods": args.pods,
                "platform": jax.devices()[0].platform,
                "phases_s": {
                    "synth": round(t_synth, 2),
                    "import": round(t_import, 2),
                    "encode": round(t_encode, 2),
                    "schedule": round(t_sched, 2),
                },
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    sys.exit(main())
