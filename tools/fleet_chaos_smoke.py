"""`make fleet-chaos-smoke`: the fleet durability plane's end-to-end
gate (docs/fleet.md, docs/resilience.md), on CPU, with REAL spawned
worker processes on distinct per-worker session dirs and the HTTP
checkpoint transport forced (`KSS_FLEET_TRANSPORT=http` — the
cross-host behavior; the same-filesystem file move would mask transport
bugs). The lock-order witness (`KSS_LOCK_CHECK=1`) is armed throughout.

Gate A — seeded chaos churn. With `net_drop:0.15,net_delay:10ms` armed
through `POST /api/v1/fleet/faultinject`, a burst of writes goes
through the router: idempotent reads retry through the drops,
non-idempotent writes surface errors honestly — and every write the
router ACKNOWLEDGED must be present afterwards.

Gate B — kill -9 loses nothing acknowledged. A session journals every
acknowledged write (`KSS_FLEET_JOURNAL_SYNC=1` ships each entry to its
ring successors BEFORE the HTTP ack); its owner worker gets `kill -9`
(no drain, no snapshot). The router detects the corpse, promotes the
successor's replica, and the session must answer through the SAME
router URL with a canonically byte-identical resource document — zero
acknowledged-write loss.

Gate C — a net_drop storm opens the breaker. With `net_drop:1.0`, the
per-worker circuit breaker opens after KSS_FLEET_BREAKER_FAILURES
consecutive failures: requests shed 503 + Retry-After WITHOUT touching
a socket. Lifting the storm, the half-open probe closes it and serving
recovers.

Gate D — end-to-end distributed-trace causality (KSS_TRACE=1 armed for
router and workers, docs/observability.md). Under seeded net faults, a
pod is scheduled through the router; the router's merged Perfetto
export (`GET /api/v1/debug/trace`) must then contain ONE trace id
shared by the router request span (with >=1 `router.attempt` child),
the owning worker's pass span, and its `device.execute` span; every
merged interval must be well-formed (`check_nesting` over the merged
document), and some retried GET must show a >=2-attempt span tree.

Exit 0 on pass, 1 with the problem list otherwise; one JSON line either
way.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# the witness wraps locks at creation: arm before the package imports
os.environ.setdefault("KSS_LOCK_CHECK", "1")
# gate D's trace plane: the router process records its own span ring
# and propagates trace context on every proxied hop
os.environ["KSS_TRACE"] = "1"

from kube_scheduler_simulator_tpu.fleet import FleetRouter  # noqa: E402
from kube_scheduler_simulator_tpu.lifecycle.checkpoint import (  # noqa: E402
    canonical_bytes,
)
from kube_scheduler_simulator_tpu.utils import telemetry  # noqa: E402


def _pod(name):
    return {
        "metadata": {"name": name},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "resources": {
                        "requests": {"cpu": "100m", "memory": "128Mi"}
                    },
                }
            ]
        },
    }


def _req(port, method, path, body=None, timeout=600):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None, dict(
                resp.headers
            )
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            return e.code, json.loads(raw) if raw else None, dict(e.headers)
        except json.JSONDecodeError:
            return e.code, None, dict(e.headers)


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.25)
    raise RuntimeError(f"timed out waiting for {what}")


def _canonical_pods(port, sid):
    code, items, _ = _req(port, "GET", f"/api/v1/sessions/{sid}/resources/pods")
    if code != 200:
        return code, None
    return code, canonical_bytes(items)


def main() -> int:
    problems: list[str] = []
    fleet_dir = tempfile.mkdtemp(prefix="kss-chaos-smoke-")
    cache_dir = tempfile.mkdtemp(prefix="kss-chaos-smoke-cache-")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KSS_LOCK_CHECK="1",
        KSS_NO_SPECULATIVE_COMPILE="1",
        KSS_JAX_CACHE_DIR=cache_dir,
        # the durability plane under test: per-write journaling with
        # inline successor shipping, a fast replication cadence, and
        # the HTTP transport forced (distinct non-shared session dirs)
        KSS_FLEET_TRANSPORT="http",
        KSS_FLEET_JOURNAL="1",
        KSS_FLEET_JOURNAL_SYNC="1",
        KSS_FLEET_REPLICAS="1",
        KSS_FLEET_REPLICATE_EVERY_S="0.3",
        # resilience knobs sized for a fast smoke
        KSS_FLEET_BREAKER_OPEN_S="0.5",
        KSS_FLEET_RETRY_BACKOFF_S="0.02",
        # gate D: span rings + trace propagation on every process
        KSS_TRACE="1",
    )
    env.pop("KSS_WORKER_ID", None)  # the router assigns identities
    env.pop("KSS_SESSION_DIR", None)  # per-worker dirs under fleet_dir

    router = FleetRouter(
        n_workers=3,
        fleet_dir=fleet_dir,
        probe_interval_s=0.5,
        env=env,
    ).start()
    result = {"ok": False}
    try:
        port = router.port

        # ---- Gate A: seeded chaos churn ------------------------------------
        code, doc, _ = _req(
            port, "POST", "/api/v1/sessions", {"id": "churn-1"}
        )
        assert code == 201, f"create churn-1: {code} {doc}"
        code, doc, _ = _req(
            port,
            "POST",
            "/api/v1/fleet/faultinject",
            {"spec": "net_drop:0.15,net_delay:10ms", "seed": 42},
        )
        if code != 200 or not doc.get("active"):
            problems.append(f"gate A: faultinject refused: {code} {doc}")
        acked: list[str] = []
        errors = 0
        for i in range(30):
            name = f"cp{i}"
            code, _, _ = _req(
                port,
                "PUT",
                "/api/v1/sessions/churn-1/resources/pods",
                _pod(name),
                timeout=30,
            )
            if code == 201:
                acked.append(name)
            else:
                errors += 1
        code, doc, _ = _req(
            port, "POST", "/api/v1/fleet/faultinject", {"spec": ""}
        )
        assert code == 200 and not doc.get("active"), "disarm failed"
        # reads may need the breaker to recover from the churn's drops
        time.sleep(0.6)
        items = _wait(
            lambda: _req(
                port, "GET", "/api/v1/sessions/churn-1/resources/pods"
            )[1]
            if _req(port, "GET", "/api/v1/sessions/churn-1/resources/pods")[0]
            == 200
            else None,
            30,
            "churn session to answer after the storm",
        )
        present = {p["metadata"]["name"] for p in items["items"]}
        lost = [n for n in acked if n not in present]
        if lost:
            problems.append(
                f"gate A: acknowledged writes lost in churn: {lost}"
            )
        if not acked:
            problems.append(
                "gate A: chaos dropped every write — nothing was exercised"
            )
        _, fdoc, _ = _req(port, "GET", "/api/v1/fleet")
        result["gateA"] = {
            "acked": len(acked),
            "writeErrors": errors,
            "routerRetries": fdoc.get("retries"),
        }

        # ---- Gate B: kill -9 loses nothing acknowledged --------------------
        code, doc, _ = _req(
            port, "POST", "/api/v1/sessions", {"id": "crash-1"}
        )
        assert code == 201, f"create crash-1: {code} {doc}"
        for i in range(5):
            code, _, _ = _req(
                port,
                "PUT",
                "/api/v1/sessions/crash-1/resources/pods",
                _pod(f"base{i}"),
            )
            assert code == 201, f"base write {i}: {code}"
        # let the ticker ship the base unit to the ring successor; the
        # tail below then rides the sync journal ship alone
        time.sleep(1.0)
        for i in range(3):
            code, _, _ = _req(
                port,
                "PUT",
                "/api/v1/sessions/crash-1/resources/pods",
                _pod(f"tail{i}"),
            )
            assert code == 201, f"tail write {i}: {code}"
        code, before = _canonical_pods(port, "crash-1")
        assert code == 200
        victim = router.worker_for("crash-1")
        victim_wid = victim.id
        victim.proc.kill()  # kill -9: no drain, no snapshot, no goodbye
        _wait(
            lambda: _req(port, "GET", "/api/v1/fleet")[1]["sessions"].get(
                "crash-1"
            )
            not in (None, victim_wid),
            120,
            f"crash-1 to re-home off {victim_wid}",
        )
        _wait(
            lambda: _canonical_pods(port, "crash-1")[0] == 200,
            60,
            "the re-homed session to answer",
        )
        code, after = _canonical_pods(port, "crash-1")
        if before != after:
            problems.append(
                "gate B: re-homed document differs from the pre-kill "
                "acknowledged state (acknowledged-write loss)"
            )
        _, fdoc, _ = _req(port, "GET", "/api/v1/fleet")
        if fdoc.get("pendingAdopts"):
            problems.append(
                f"gate B: adoptions left pending: {fdoc['pendingAdopts']}"
            )
        result["gateB"] = {
            "victim": victim_wid,
            "successor": fdoc["sessions"].get("crash-1"),
            "rehomedSessions": fdoc.get("rehomedSessions"),
        }

        # ---- Gate C: a net_drop storm opens the breaker --------------------
        code, doc, _ = _req(
            port,
            "POST",
            "/api/v1/fleet/faultinject",
            {"spec": "net_drop:1.0", "seed": 7},
        )
        assert code == 200 and doc.get("active"), "storm arm failed"
        saw_shed = saw_retry_after = False
        for _ in range(20):
            code, doc, headers = _req(
                port,
                "GET",
                "/api/v1/sessions/crash-1/resources/pods",
                timeout=30,
            )
            if code == 503:
                saw_shed = True
                if headers.get("Retry-After"):
                    saw_retry_after = True
                if (doc or {}).get("kind") == "CircuitOpen":
                    break
        if not saw_shed:
            problems.append("gate C: total net_drop never shed a request")
        if not saw_retry_after:
            problems.append("gate C: sheds carried no Retry-After")
        _, fdoc, _ = _req(port, "GET", "/api/v1/fleet")
        if not fdoc.get("breakerOpens"):
            problems.append(
                f"gate C: breaker never opened (doc: {fdoc.get('workers')})"
            )
        code, doc, _ = _req(
            port, "POST", "/api/v1/fleet/faultinject", {"spec": ""}
        )
        assert code == 200 and not doc.get("active"), "storm disarm failed"
        time.sleep(0.6)  # past KSS_FLEET_BREAKER_OPEN_S
        _wait(
            lambda: _req(
                port, "GET", "/api/v1/sessions/crash-1/resources/pods"
            )[0]
            == 200,
            30,
            "the breaker's half-open probe to close it",
        )
        _, fdoc, _ = _req(port, "GET", "/api/v1/fleet")
        owner = fdoc["sessions"].get("crash-1")
        breakers = {w["id"]: w["breaker"] for w in fdoc["workers"]}
        if breakers.get(owner) != "closed":
            problems.append(
                f"gate C: owner breaker not closed after recovery: {breakers}"
            )
        result["gateC"] = {
            "breakerOpens": fdoc.get("breakerOpens"),
            "breakers": breakers,
        }

        # ---- Gate D: end-to-end distributed-trace causality ----------------
        code, doc, _ = _req(port, "POST", "/api/v1/sessions", {"id": "trace-1"})
        assert code == 201, f"create trace-1: {code} {doc}"
        base = "/api/v1/sessions/trace-1"
        code, _, _ = _req(
            port,
            "PUT",
            f"{base}/resources/nodes",
            {
                "metadata": {"name": "tn0"},
                "status": {
                    "allocatable": {
                        "cpu": "8", "memory": "16Gi", "pods": "110"
                    }
                },
            },
        )
        assert code == 201, f"trace node: {code}"
        code, _, _ = _req(
            port, "PUT", f"{base}/resources/pods", _pod("tp0")
        )
        assert code == 201, f"trace pod: {code}"
        # seeded net faults: idempotent GETs retry through the drops
        # (the >=2-attempt span tree); the schedule POST is single-
        # attempt per inbound request, retried here at the client
        code, doc, _ = _req(
            port,
            "POST",
            "/api/v1/fleet/faultinject",
            {"spec": "net_drop:0.3", "seed": 11},
        )
        assert code == 200 and doc.get("active"), "gate D arm failed"
        scheduled = False
        for _ in range(25):
            code, sdoc, _ = _req(port, "POST", f"{base}/schedule", timeout=60)
            if code == 200 and (sdoc or {}).get("scheduled"):
                scheduled = True
                break
        for _ in range(15):
            _req(port, "GET", f"{base}/resources/pods", timeout=30)
        code, doc, _ = _req(
            port, "POST", "/api/v1/fleet/faultinject", {"spec": ""}
        )
        assert code == 200 and not doc.get("active"), "gate D disarm failed"
        if not scheduled:
            problems.append("gate D: pod never scheduled through the storm")
        # the request ring names the schedule request's trace id and
        # the retried GETs' attempt counts
        _, ring, _ = _req(port, "GET", "/api/v1/fleet/requests")
        entries = (ring or {}).get("requests") or []
        sched = [
            e
            for e in entries
            if e.get("route") == f"{base}/schedule" and e.get("status") == 200
        ]
        retried_gets = [
            e
            for e in entries
            if e.get("method") == "GET" and (e.get("attempts") or 0) >= 2
        ]
        tid = sched[-1]["trace"] if sched else None
        if tid is None:
            problems.append(
                "gate D: request ring has no traced 200 schedule entry"
            )
        if not retried_gets:
            problems.append(
                "gate D: no GET retried under the seeded drops "
                "(no >=2-attempt span tree to check)"
            )
        _, merged, _ = _req(port, "GET", "/api/v1/debug/trace")
        events = (merged or {}).get("traceEvents") or []
        other = (merged or {}).get("otherData") or {}
        if not other.get("merged") or not other.get("tracingEnabled"):
            problems.append(f"gate D: merged export not armed: {other}")
        if len(other.get("tracks") or []) < 3:
            problems.append(
                f"gate D: expected router + >=2 worker tracks, got "
                f"{other.get('tracks')}"
            )
        try:
            telemetry.check_nesting(
                events, dropped=int(other.get("droppedEvents") or 0)
            )
        except ValueError as e:
            problems.append(f"gate D: merged intervals malformed: {e}")

        def _with_trace(t):
            return [
                ev
                for ev in events
                if (ev.get("args") or {}).get("trace") == t
            ]

        if tid is not None:
            tev = _with_trace(tid)
            req_spans = [
                ev
                for ev in tev
                if ev.get("name") == "router.request" and ev.get("ph") == "B"
            ]
            attempt_spans = [
                ev
                for ev in tev
                if ev.get("name") == "router.attempt" and ev.get("ph") == "B"
            ]
            pass_spans = [
                ev
                for ev in tev
                if str(ev.get("name", "")).startswith("pass.")
                and ev.get("pid") != 0
            ]
            device_spans = [
                ev
                for ev in tev
                if ev.get("name") == "device.execute" and ev.get("pid") != 0
            ]
            if not req_spans:
                problems.append(
                    "gate D: no router.request span carries the "
                    "scheduled pod's trace id"
                )
            if not attempt_spans:
                problems.append(
                    "gate D: the traced request has no router.attempt child"
                )
            if not pass_spans:
                problems.append(
                    "gate D: no worker pass span carries the trace id "
                    "(context not adopted at the HTTP chokepoint?)"
                )
            if not device_spans:
                problems.append(
                    "gate D: no device.execute span carries the trace id"
                )
            result["gateD"] = {
                "trace": tid,
                "attemptSpans": len(attempt_spans),
                "passSpans": len(pass_spans),
                "deviceSpans": len(device_spans),
                "retriedGets": len(retried_gets),
                "tracks": other.get("tracks"),
            }
        if retried_gets:
            rtid = retried_gets[-1].get("trace")
            r_attempts = [
                ev
                for ev in _with_trace(rtid)
                if ev.get("name") == "router.attempt" and ev.get("ph") == "B"
            ]
            if len(r_attempts) < 2:
                problems.append(
                    f"gate D: retried GET trace {rtid} shows "
                    f"{len(r_attempts)} attempt span(s), expected >=2"
                )
    finally:
        router.shutdown(drain=True)

    result["ok"] = not problems
    result["problems"] = problems
    print(json.dumps(result), flush=True)
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
