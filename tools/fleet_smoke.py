"""`make fleet-smoke`: the horizontal serving fleet's end-to-end gate
(docs/fleet.md), on CPU, with REAL spawned worker processes:

Gate A — the shared compile CDN. A 2-worker fleet boots over ONE shared
bundle dir. A session pinned to one worker schedules (compiles + saves
bundles); a session pinned to the OTHER worker schedules the same
shape and must resolve every engine program from the store:
`bundleMisses == 0`, `bundleLoads >= 1` — any worker's compile is every
worker's sub-second warm start.

Gate B — worker death loses nothing. A session writes a sentinel pod,
its owner worker gets `kill -TERM` (the zero-loss drain: snapshots
everything, exits 0), the router detects the death and re-homes the
session to its ring successor — which must answer with the sentinel
intact through the SAME router URL.

Gate C — the rolling restart stays observable. `POST
/api/v1/fleet/roll` restarts the (remaining) fleet one worker at a
time; throughout the roll, `/api/v1/metrics` and `/api/v1/fleet` must
keep answering; afterwards every spawned worker is ready again and the
re-homed session still has its state.

Exit 0 on pass, 1 with the problem list otherwise; one JSON line either
way.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from kube_scheduler_simulator_tpu.fleet import FleetRouter  # noqa: E402
from kube_scheduler_simulator_tpu.utils.bundles import (  # noqa: E402
    BUNDLE_SUFFIX,
)

NODE = {
    "metadata": {"name": "fn0"},
    "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
}


def _pod(name):
    return {
        "metadata": {"name": name},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "resources": {
                        "requests": {"cpu": "250m", "memory": "256Mi"}
                    },
                }
            ]
        },
    }


def _req(port, method, path, body=None, timeout=600):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            return e.code, json.loads(raw) if raw else None
        except json.JSONDecodeError:
            return e.code, None


def _create_session_on(router, target_wid, prefix):
    """Create sessions until one's ring owner is `target_wid` (ids are
    free; the ring decides — a handful of tries suffices)."""
    for i in range(64):
        sid = f"{prefix}-{i}"
        w, placed = router.place_session({"id": sid})
        if w is not None and w.id == target_wid:
            code, doc = _req(router.port, "POST", "/api/v1/sessions", {"id": sid})
            if code != 201:
                raise RuntimeError(f"create {sid} on {target_wid}: {code} {doc}")
            return sid
    raise RuntimeError(f"no id hashed to {target_wid} in 64 tries")


def _schedule_session(router, sid, pods):
    base = f"/api/v1/sessions/{sid}"
    code, _ = _req(router.port, "PUT", f"{base}/resources/nodes", NODE)
    assert code == 201, f"node put: {code}"
    for name in pods:
        code, _ = _req(router.port, "PUT", f"{base}/resources/pods", _pod(name))
        assert code == 201, f"pod put: {code}"
    code, out = _req(router.port, "POST", f"{base}/schedule")
    if code != 200:
        raise RuntimeError(f"schedule on {sid}: {code} {out}")
    return out


def _worker_bundles(router, wid):
    _, doc = _req(router.port, "GET", "/api/v1/metrics")
    wdoc = doc["workers"].get(wid) or {}
    return wdoc.get("bundles") or {}


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.25)
    raise RuntimeError(f"timed out waiting for {what}")


def main() -> int:
    problems: list[str] = []
    fleet_dir = tempfile.mkdtemp(prefix="kss-fleet-smoke-")
    cache_dir = tempfile.mkdtemp(prefix="kss-fleet-smoke-cache-")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        KSS_AOT_BUNDLES="1",
        KSS_JAX_CACHE_DIR=cache_dir,
        KSS_NO_SPECULATIVE_COMPILE="1",
    )
    env.pop("KSS_WORKER_ID", None)  # the router assigns identities
    env.pop("KSS_BUNDLE_DIR", None)  # ONE shared store under fleet_dir

    # spawned children inherit the scrubbed env above, not os.environ
    router = FleetRouter(
        n_workers=2,
        fleet_dir=fleet_dir,
        probe_interval_s=0.5,
        env=env,
    ).start()
    result = {"ok": False}
    try:
        bundle_dir = router.bundle_dir

        # ---- Gate A: the shared compile CDN --------------------------------
        sid_a = _create_session_on(router, "w0", "cdn-a")
        _schedule_session(router, sid_a, [f"ap{i}" for i in range(4)])
        _wait(
            lambda: [
                f
                for f in os.listdir(bundle_dir)
                if f.endswith(BUNDLE_SUFFIX)
            ],
            120,
            "worker w0's bundle saves to land in the shared store",
        )
        sid_b = _create_session_on(router, "w1", "cdn-b")
        _schedule_session(router, sid_b, [f"ap{i}" for i in range(4)])
        b_stats = _worker_bundles(router, "w1")
        if b_stats.get("bundleMisses") != 0:
            problems.append(
                f"gate A: worker w1 compiled engine programs despite the "
                f"shared store: {b_stats}"
            )
        if not b_stats.get("bundleLoads"):
            problems.append(
                f"gate A: worker w1 loaded no bundles: {b_stats}"
            )
        result["gateA"] = {"w1Bundles": b_stats}

        # ---- Gate B: worker death loses nothing ----------------------------
        owner = router.worker_for(sid_b)
        victim_wid = owner.id
        base = f"/api/v1/sessions/{sid_b}"
        code, _ = _req(router.port, "PUT", f"{base}/resources/pods", _pod("sentinel"))
        assert code == 201
        owner.proc.terminate()  # kill -TERM: the zero-loss drain
        _wait(
            lambda: _req(router.port, "GET", "/api/v1/fleet")[1]["sessions"].get(
                sid_b
            )
            not in (None, victim_wid),
            120,
            f"session {sid_b} to re-home off {victim_wid}",
        )
        code, items = _req(router.port, "GET", f"{base}/resources/pods")
        names = (
            {p["metadata"]["name"] for p in items["items"]}
            if code == 200
            else set()
        )
        if code != 200 or "sentinel" not in names:
            problems.append(
                f"gate B: re-homed session lost writes "
                f"(status {code}, pods {sorted(names)})"
            )
        _, fdoc = _req(router.port, "GET", "/api/v1/fleet")
        successor = fdoc["sessions"].get(sid_b)
        result["gateB"] = {
            "victim": victim_wid,
            "successor": successor,
            "rehomedSessions": fdoc["rehomedSessions"],
        }

        # ---- Gate C: rolling restart stays observable ----------------------
        code, doc = _req(router.port, "POST", "/api/v1/fleet/roll")
        if code != 202 or not doc.get("started"):
            problems.append(f"gate C: roll refused: {code} {doc}")
        scrapes = 0
        while True:
            code_m, _ = _req(router.port, "GET", "/api/v1/metrics")
            code_f, fdoc = _req(router.port, "GET", "/api/v1/fleet")
            if code_m != 200 or code_f != 200:
                problems.append(
                    f"gate C: scrape went dark mid-roll "
                    f"(metrics {code_m}, fleet {code_f})"
                )
                break
            scrapes += 1
            if not fdoc["roll"]["rolling"]:
                break
            time.sleep(0.5)
        states = {w["id"]: w["state"] for w in fdoc["workers"]}
        not_ready = sorted(
            wid for wid, st in states.items() if st != "ready"
        )
        if not_ready:
            problems.append(
                f"gate C: workers not ready after the roll: "
                f"{ {w: states[w] for w in not_ready} }"
            )
        code, items = _req(router.port, "GET", f"{base}/resources/pods")
        names = (
            {p["metadata"]["name"] for p in items["items"]}
            if code == 200
            else set()
        )
        if code != 200 or "sentinel" not in names:
            problems.append(
                f"gate C: session state lost across the roll "
                f"(status {code}, pods {sorted(names)})"
            )
        result["gateC"] = {
            "scrapesDuringRoll": scrapes,
            "rolled": fdoc["roll"]["rolled"],
            "workerStates": states,
        }
    finally:
        router.shutdown(drain=True)

    result["ok"] = not problems
    result["problems"] = problems
    print(json.dumps(result), flush=True)
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
