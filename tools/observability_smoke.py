"""`make observability-smoke`: the unified telemetry plane end-to-end
on CPU (docs/observability.md). Three gates, one JSON line:

1. **Flight recorder → Perfetto** — a short async-pipelined chaos
   timeline runs with tracing ON; the exported Chrome trace-event JSON
   must load back as well-formed JSON, every thread's B/E spans must be
   balanced (`telemetry.check_nesting`), and the async pipeline's
   overlap must be PRESENT in the data: a `device.execute` X span of
   pass k overlapping a host-side `lifecycle.events` span of pass k+1.

2. **Prometheus** — `GET /api/v1/metrics?format=prometheus` against a
   live server is scraped through the REAL text-format parser
   (`metrics.parse_prometheus_text`), which enforces TYPE lines,
   sample grammar, and histogram bucket semantics.

3. **SSE** — `GET /api/v1/events` yields at least one event.

4. **Fleet & memory observatory** (docs/observability.md) — the traced
   chaos run is armed with fleet stats and must leave ≥1 `fleet.*`
   counter track in the Perfetto export; against the live server,
   `GET /api/v1/timeseries` must answer a non-empty window and the new
   `kss_fleet_*` gauges must survive the real Prometheus parse.

5. **SLO alert lifecycle** (docs/observability.md) — a sim-time chaos
   run with `compile_slow`/`device_error` faults injected must drive
   an alert through the FULL pending → firing → resolved lifecycle
   (the faults make the early compile-bearing passes slow; sim time
   then slides the burn windows past the bad era). All three surfaces
   are checked: the transition history at `GET /api/v1/alerts`, the
   `kss_slo_*`/`kss_alert_*` families through the strict Prometheus
   parse, and a LIVE SSE `alert` event observed while a PUT-overridden
   objective breaches in the serving process.

6. **Exemplars** — `?format=openmetrics` exemplars on the pass-latency
   histogram must resolve to pass ids present as span `args.pass` in
   the recorder's Perfetto events (the bucket → trace link).

Exit 0 on pass. Small enough for CI (seconds, CPU-only).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _chaos_spec_dict() -> dict:
    nodes = [
        {
            "metadata": {"name": f"o{i}"},
            "status": {
                "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
            },
        }
        for i in range(4)
    ]
    return {
        "name": "observability-smoke",
        "seed": 3,
        "horizon": 20.0,
        "schedulerMode": "gang",
        "pipeline": "async",
        "snapshot": {"nodes": nodes},
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 1.0,
                "count": 12,
                "template": {
                    "metadata": {"name": "churn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
        "faults": [
            {"at": 6.0, "action": "cordon", "node": "o0"},
            {"at": 12.0, "action": "uncordon", "node": "o0"},
        ],
    }


def _async_overlap(intervals: list[dict]) -> "float | None":
    """Largest overlap (seconds) between a device-execute window of pass
    k and a host lifecycle.events span of pass k+1; None when no pair
    overlaps — the async pipeline's signature, asserted not eyeballed."""
    from kube_scheduler_simulator_tpu.utils import telemetry

    best = None
    device = [
        iv
        for iv in intervals
        if iv["name"] == "device.execute" and iv["tid"] == telemetry.DEVICE_TID
    ]
    hosts = [iv for iv in intervals if iv["name"] == "lifecycle.events"]
    for d in device:
        k = d["args"].get("pass")
        if k is None:
            continue
        for h in hosts:
            if h["args"].get("pass") != k + 1:
                continue
            overlap = min(d["end_us"], h["end_us"]) - max(
                d["start_us"], h["start_us"]
            )
            if overlap > 0 and (best is None or overlap > best):
                best = overlap
    return None if best is None else best / 1e6


def _slo_chaos_spec_dict() -> dict:
    """The alert-gate timeline: a sim-time run long enough for the
    burn windows to slide past the injected-fault era. The early
    compile-bearing passes are slow (compile_slow + the device-error
    ladder walk), breaching the tightened passLatency objective; warm
    passes are fast, and the late sim-time ticks carry the windows
    clear — pending → firing → resolved on one seeded run."""
    nodes = [
        {
            "metadata": {"name": f"a{i}"},
            "status": {
                "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
            },
        }
        for i in range(4)
    ]
    return {
        "name": "slo-alert-smoke",
        "seed": 11,
        "horizon": 700.0,
        "schedulerMode": "gang",
        "pipeline": "sync",
        "snapshot": {"nodes": nodes},
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 0.05,
                "count": 30,
                "template": {
                    "metadata": {"name": "slochurn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
        # late cordon flap: guarantees sim-time ticks well past the
        # fast window even if the arrival tail lands early
        "faults": [
            {"at": 620.0, "action": "cordon", "node": "a0"},
            {"at": 640.0, "action": "uncordon", "node": "a0"},
        ],
    }


def _slo_alert_gate() -> "tuple[dict, list[str]]":
    """Gate 5: injected compile_slow/device_error faults drive an SLO
    alert through pending → firing → resolved on a sim-time chaos run
    (the plane's clock follows the timeline, so the 5-minute fast
    window slides in simulated seconds)."""
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
    from kube_scheduler_simulator_tpu.utils import slo

    problems: list[str] = []
    log = slo.reset_alert_log(256)
    overrides = {
        "KSS_SLO": "1",
        # tight latency objective: the compile-bearing passes (plus the
        # injected 0.3s compile_slow and the device-error ladder walk)
        # breach it; warm gang passes (~tens of ms) satisfy it
        "KSS_SLO_OBJECTIVES": "passLatency:target=0.97,threshold=0.25",
        # softened burn thresholds: the gate's bad era is a handful of
        # compile-bearing passes, and the default page-tier 14.4x would
        # dilute below the condition before the pending hold elapses
        "KSS_SLO_BURN_FAST": "5",
        "KSS_SLO_BURN_SLOW": "2",
        "KSS_SLO_ALERT_FOR_S": "10",
        "KSS_FAULT_INJECT": "compile_slow:0.3s,device_error:1.0",
        "KSS_FAULT_INJECT_SEED": "7",
    }
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        eng = LifecycleEngine(ChaosSpec.from_dict(_slo_chaos_spec_dict()))
        result = eng.run()
        if result["phase"] != "Succeeded":
            problems.append(f"slo chaos run phase {result['phase']!r}")
        # one explicit final evaluation at the horizon: the resolved
        # transition must not depend on the last timeline tick's timing
        eng.scheduler.metrics.slo_tick(max(float(eng.sim_time), 700.0))
        slo_doc = eng.scheduler.metrics.snapshot()["slo"]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    states = [
        ev["state"]
        for ev in log.snapshot()
        if ev.get("objective") == "passLatency"
    ]
    for needed in ("pending", "firing", "resolved"):
        if needed not in states:
            problems.append(
                f"alert lifecycle missing {needed!r} (saw {states})"
            )
    firsts = [
        states.index(s)
        for s in ("pending", "firing", "resolved")
        if s in states
    ]
    if firsts != sorted(firsts):
        problems.append(f"alert lifecycle out of order: {states}")
    if not slo_doc.get("enabled"):
        problems.append("metrics snapshot carries no armed slo block")
    fields = {
        "alert_transitions": states,
        "alerts_fired": log.counters()["fired"],
        "slo_compliance_pass_latency": (
            slo_doc.get("objectives", {})
            .get("passLatency", {})
            .get("compliance")
        ),
    }
    return fields, problems


def _trace_gate() -> "tuple[dict, list[str]]":
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
    from kube_scheduler_simulator_tpu.utils import fleetstats, telemetry

    problems: list[str] = []
    recorder = telemetry.SpanRecorder(capacity=65536)
    telemetry.activate(recorder)
    # the fleet observatory rides the same traced run: per-pass samples
    # must land in the ring AND emit fleet.* counter tracks
    fleet_rec = fleetstats.FleetRecorder(capacity=1024)
    fleetstats.activate(fleet_rec)
    try:
        eng = LifecycleEngine(ChaosSpec.from_dict(_chaos_spec_dict()))
        result = eng.run()
        if result["phase"] != "Succeeded":
            problems.append(f"chaos run phase {result['phase']!r}")
        out = os.path.join(tempfile.mkdtemp(prefix="kss-obs-"), "trace.json")
        n = telemetry.dump_chrome_trace(out, recorder)
    finally:
        telemetry.deactivate()
        fleetstats.deactivate()
    if fleet_rec.emitted < 1:
        problems.append("fleet observatory recorded no samples")
    with open(out) as f:
        doc = json.load(f)  # raises on malformed JSON: the gate
    events = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    if len(events) != n:
        problems.append(f"export wrote {n} events, file carries {len(events)}")
    if not events:
        problems.append("flight recorder captured nothing")
    try:
        telemetry.check_nesting(
            events, dropped=doc["otherData"].get("droppedEvents", 0)
        )
    except ValueError as e:
        problems.append(f"span nesting ill-formed: {e}")
    overlap_s = _async_overlap(telemetry.span_intervals(events))
    if overlap_s is None:
        problems.append(
            "no device-execute span of pass k overlaps a host "
            "lifecycle.events span of pass k+1"
        )
    # fleet counter tracks in the export: Perfetto renders these as
    # stepped areas next to the pass spans (docs/observability.md)
    fleet_counters = {
        e["name"]
        for e in events
        if e.get("ph") == "C" and str(e.get("name", "")).startswith("fleet.")
    }
    if not fleet_counters:
        problems.append("no fleet.* counter track in the Perfetto export")
    fields = {
        "trace_file": out,
        "trace_events": len(events),
        "async_overlap_s": round(overlap_s, 6) if overlap_s else 0.0,
        "fleet_samples": fleet_rec.emitted,
        "fleet_counter_tracks": sorted(fleet_counters),
    }
    return fields, problems


def _server_gates() -> "tuple[dict, list[str]]":
    import urllib.request

    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
    from kube_scheduler_simulator_tpu.utils import fleetstats
    from kube_scheduler_simulator_tpu.utils.metrics import (
        parse_prometheus_text,
    )

    from kube_scheduler_simulator_tpu.utils import telemetry

    problems: list[str] = []
    fleetstats.activate(fleetstats.FleetRecorder(capacity=256))
    # a live recorder over the server's passes: the exemplar gate
    # resolves openmetrics span_ids against these events' args.pass
    recorder = telemetry.SpanRecorder(capacity=16384)
    telemetry.activate(recorder)
    server = SimulatorServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        # one real pass so counters and the latency histogram are live
        server.service.store.apply(
            "nodes",
            {
                "metadata": {"name": "s0"},
                "status": {
                    "allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}
                },
            },
        )
        server.service.store.apply(
            "pods",
            {
                "metadata": {"name": "sp0"},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "resources": {"requests": {"cpu": "100m"}},
                        }
                    ]
                },
            },
        )
        server.service.scheduler.schedule()
        with urllib.request.urlopen(
            f"{base}/api/v1/metrics?format=prometheus", timeout=30
        ) as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        families = parse_prometheus_text(text)  # raises on malformed text
        if "text/plain" not in ctype:
            problems.append(f"prometheus content-type {ctype!r}")
        for needed in (
            "kss_passes_total",
            "kss_pass_latency_seconds",
            "kss_uptime_seconds",
            # the fleet observatory gauges (utils/fleetstats.py) must
            # render AND survive the strict parse above
            "kss_fleet_pending_pods",
            "kss_fleet_utilization_mean",
            "kss_fleet_fragmentation_index",
            "kss_fleet_samples_total",
        ):
            if needed not in families:
                problems.append(f"metric family {needed} missing")
        if families.get("kss_passes_total", {}).get("samples", [(0, 0, 0)])[
            0
        ][2] < 1:
            problems.append("kss_passes_total did not count the pass")
        # the observatory's sample window must be non-empty after a pass
        with urllib.request.urlopen(
            f"{base}/api/v1/timeseries", timeout=30
        ) as r:
            ts = json.loads(r.read().decode())
        if not ts.get("enabled"):
            problems.append("/api/v1/timeseries reports stats disabled")
        if not ts.get("samples"):
            problems.append("/api/v1/timeseries window is empty after a pass")
        else:
            s = ts["samples"][-1]
            for field in ("devices", "buffers", "fleet"):
                if field not in s:
                    problems.append(f"timeseries sample missing {field!r}")
        # SSE: the stream must yield >= 1 event promptly
        req = urllib.request.Request(f"{base}/api/v1/events")
        sse_event = None
        with urllib.request.urlopen(req, timeout=30) as r:
            for _ in range(32):
                line = r.readline().decode()
                if line.startswith("event:"):
                    sse_event = line.split(":", 1)[1].strip()
                    break
        if sse_event is None:
            problems.append("SSE stream yielded no event")
        # gate 6 — exemplars: the openmetrics exposition's pass-latency
        # bucket exemplars must resolve to pass ids present as span
        # args.pass in the recorder (the bucket -> Perfetto link)
        with urllib.request.urlopen(
            f"{base}/api/v1/metrics?format=openmetrics", timeout=30
        ) as r:
            om_ctype = r.headers.get("Content-Type", "")
            om_text = r.read().decode()
        om_families = parse_prometheus_text(om_text)
        if "openmetrics-text" not in om_ctype:
            problems.append(f"openmetrics content-type {om_ctype!r}")
        if not om_text.rstrip().endswith("# EOF"):
            problems.append("openmetrics exposition lacks the # EOF terminator")
        exemplars = om_families.get("kss_pass_latency_seconds", {}).get(
            "exemplars", []
        )
        span_ids = {
            ex_labels.get("span_id")
            for _n, _l, ex_labels, _v in exemplars
            if ex_labels.get("span_id")
        }
        if not span_ids:
            problems.append(
                "no exemplar on the pass-latency histogram buckets"
            )
        trace_pass_ids = {
            str((e.get("args") or {}).get("pass"))
            for e in recorder.snapshot()
            if (e.get("args") or {}).get("pass") is not None
        }
        unresolved = span_ids - trace_pass_ids
        if span_ids and unresolved:
            problems.append(
                f"exemplar span ids {sorted(unresolved)} absent from the "
                f"Perfetto trace's span pass ids"
            )
        # gate 5's SSE surface: a LIVE alert event while a
        # PUT-overridden objective breaches in the serving process
        put = urllib.request.Request(
            f"{base}/api/v1/slo",
            data=json.dumps(
                {
                    "objectives": {
                        "passLatency": {"target": 0.99, "threshold": 1e-9}
                    },
                    "forSeconds": 0,
                }
            ).encode(),
            method="PUT",
        )
        with urllib.request.urlopen(put, timeout=30) as r:
            json.loads(r.read().decode())
        sse_alert = None
        req = urllib.request.Request(f"{base}/api/v1/events")
        with urllib.request.urlopen(req, timeout=30) as r:
            # breach while subscribed: two passes + two evaluations
            # (GET /alerts evaluates) walk pending then firing
            for _ in range(2):
                server.service.scheduler.schedule()
                with urllib.request.urlopen(
                    f"{base}/api/v1/alerts", timeout=30
                ) as ar:
                    json.loads(ar.read().decode())
            for _ in range(256):
                line = r.readline().decode()
                if not line:
                    break
                if line.startswith("event:") and "alert" in line:
                    sse_alert = line.split(":", 1)[1].strip()
                    break
        if sse_alert != "alert":
            problems.append("no live SSE alert event observed")
        with urllib.request.urlopen(
            f"{base}/api/v1/alerts", timeout=30
        ) as r:
            alerts_doc = json.loads(r.read().decode())
        if not alerts_doc.get("enabled"):
            problems.append("/api/v1/alerts reports the plane unarmed")
        http_states = {
            ev.get("state") for ev in alerts_doc.get("history") or ()
        }
        if "firing" not in http_states:
            problems.append(
                f"/api/v1/alerts history carries no firing transition "
                f"(states {sorted(http_states)})"
            )
        with urllib.request.urlopen(
            f"{base}/api/v1/metrics?format=prometheus", timeout=30
        ) as r:
            post_alert = parse_prometheus_text(r.read().decode())
        for fam in (
            "kss_slo_compliance",
            "kss_slo_burn_rate_fast",
            "kss_alert_state",
            "kss_alerts_fired_total",
        ):
            if fam not in post_alert:
                problems.append(f"metric family {fam} missing post-alert")
        fields = {
            "prometheus_families": len(families),
            "sse_first_event": sse_event or "",
            "sse_alert_event": sse_alert or "",
            "timeseries_samples": len(ts.get("samples") or ()),
            "exemplar_span_ids": sorted(span_ids),
        }
        return fields, problems
    finally:
        server.shutdown()
        telemetry.deactivate()
        fleetstats.deactivate()


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runnable from a bare checkout: the package lives at the repo root
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from kube_scheduler_simulator_tpu.utils.compilecache import (
        enable_compile_cache,
    )

    enable_compile_cache()
    trace_fields, problems = _trace_gate()
    # the alert gate runs BEFORE the server gates: its transition
    # history stays in the process-wide ring, so GET /api/v1/alerts
    # against the live server serves the full injected-fault lifecycle
    slo_fields, slo_problems = _slo_alert_gate()
    problems += slo_problems
    server_fields, server_problems = _server_gates()
    problems += server_problems
    line = {
        "config": "observability_smoke",
        **trace_fields,
        **slo_fields,
        **server_fields,
    }
    print(json.dumps(line), flush=True)
    if problems:
        print(
            "observability-smoke FAILED: " + "; ".join(problems),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
