"""`make observability-smoke`: the unified telemetry plane end-to-end
on CPU (docs/observability.md). Three gates, one JSON line:

1. **Flight recorder → Perfetto** — a short async-pipelined chaos
   timeline runs with tracing ON; the exported Chrome trace-event JSON
   must load back as well-formed JSON, every thread's B/E spans must be
   balanced (`telemetry.check_nesting`), and the async pipeline's
   overlap must be PRESENT in the data: a `device.execute` X span of
   pass k overlapping a host-side `lifecycle.events` span of pass k+1.

2. **Prometheus** — `GET /api/v1/metrics?format=prometheus` against a
   live server is scraped through the REAL text-format parser
   (`metrics.parse_prometheus_text`), which enforces TYPE lines,
   sample grammar, and histogram bucket semantics.

3. **SSE** — `GET /api/v1/events` yields at least one event.

4. **Fleet & memory observatory** (docs/observability.md) — the traced
   chaos run is armed with fleet stats and must leave ≥1 `fleet.*`
   counter track in the Perfetto export; against the live server,
   `GET /api/v1/timeseries` must answer a non-empty window and the new
   `kss_fleet_*` gauges must survive the real Prometheus parse.

Exit 0 on pass. Small enough for CI (seconds, CPU-only).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _chaos_spec_dict() -> dict:
    nodes = [
        {
            "metadata": {"name": f"o{i}"},
            "status": {
                "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
            },
        }
        for i in range(4)
    ]
    return {
        "name": "observability-smoke",
        "seed": 3,
        "horizon": 20.0,
        "schedulerMode": "gang",
        "pipeline": "async",
        "snapshot": {"nodes": nodes},
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 1.0,
                "count": 12,
                "template": {
                    "metadata": {"name": "churn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
        "faults": [
            {"at": 6.0, "action": "cordon", "node": "o0"},
            {"at": 12.0, "action": "uncordon", "node": "o0"},
        ],
    }


def _async_overlap(intervals: list[dict]) -> "float | None":
    """Largest overlap (seconds) between a device-execute window of pass
    k and a host lifecycle.events span of pass k+1; None when no pair
    overlaps — the async pipeline's signature, asserted not eyeballed."""
    from kube_scheduler_simulator_tpu.utils import telemetry

    best = None
    device = [
        iv
        for iv in intervals
        if iv["name"] == "device.execute" and iv["tid"] == telemetry.DEVICE_TID
    ]
    hosts = [iv for iv in intervals if iv["name"] == "lifecycle.events"]
    for d in device:
        k = d["args"].get("pass")
        if k is None:
            continue
        for h in hosts:
            if h["args"].get("pass") != k + 1:
                continue
            overlap = min(d["end_us"], h["end_us"]) - max(
                d["start_us"], h["start_us"]
            )
            if overlap > 0 and (best is None or overlap > best):
                best = overlap
    return None if best is None else best / 1e6


def _trace_gate() -> "tuple[dict, list[str]]":
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
    from kube_scheduler_simulator_tpu.utils import fleetstats, telemetry

    problems: list[str] = []
    recorder = telemetry.SpanRecorder(capacity=65536)
    telemetry.activate(recorder)
    # the fleet observatory rides the same traced run: per-pass samples
    # must land in the ring AND emit fleet.* counter tracks
    fleet_rec = fleetstats.FleetRecorder(capacity=1024)
    fleetstats.activate(fleet_rec)
    try:
        eng = LifecycleEngine(ChaosSpec.from_dict(_chaos_spec_dict()))
        result = eng.run()
        if result["phase"] != "Succeeded":
            problems.append(f"chaos run phase {result['phase']!r}")
        out = os.path.join(tempfile.mkdtemp(prefix="kss-obs-"), "trace.json")
        n = telemetry.dump_chrome_trace(out, recorder)
    finally:
        telemetry.deactivate()
        fleetstats.deactivate()
    if fleet_rec.emitted < 1:
        problems.append("fleet observatory recorded no samples")
    with open(out) as f:
        doc = json.load(f)  # raises on malformed JSON: the gate
    events = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    if len(events) != n:
        problems.append(f"export wrote {n} events, file carries {len(events)}")
    if not events:
        problems.append("flight recorder captured nothing")
    try:
        telemetry.check_nesting(
            events, dropped=doc["otherData"].get("droppedEvents", 0)
        )
    except ValueError as e:
        problems.append(f"span nesting ill-formed: {e}")
    overlap_s = _async_overlap(telemetry.span_intervals(events))
    if overlap_s is None:
        problems.append(
            "no device-execute span of pass k overlaps a host "
            "lifecycle.events span of pass k+1"
        )
    # fleet counter tracks in the export: Perfetto renders these as
    # stepped areas next to the pass spans (docs/observability.md)
    fleet_counters = {
        e["name"]
        for e in events
        if e.get("ph") == "C" and str(e.get("name", "")).startswith("fleet.")
    }
    if not fleet_counters:
        problems.append("no fleet.* counter track in the Perfetto export")
    fields = {
        "trace_file": out,
        "trace_events": len(events),
        "async_overlap_s": round(overlap_s, 6) if overlap_s else 0.0,
        "fleet_samples": fleet_rec.emitted,
        "fleet_counter_tracks": sorted(fleet_counters),
    }
    return fields, problems


def _server_gates() -> "tuple[dict, list[str]]":
    import urllib.request

    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
    from kube_scheduler_simulator_tpu.utils import fleetstats
    from kube_scheduler_simulator_tpu.utils.metrics import (
        parse_prometheus_text,
    )

    problems: list[str] = []
    fleetstats.activate(fleetstats.FleetRecorder(capacity=256))
    server = SimulatorServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        # one real pass so counters and the latency histogram are live
        server.service.store.apply(
            "nodes",
            {
                "metadata": {"name": "s0"},
                "status": {
                    "allocatable": {"cpu": "4", "memory": "8Gi", "pods": "10"}
                },
            },
        )
        server.service.store.apply(
            "pods",
            {
                "metadata": {"name": "sp0"},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "resources": {"requests": {"cpu": "100m"}},
                        }
                    ]
                },
            },
        )
        server.service.scheduler.schedule()
        with urllib.request.urlopen(
            f"{base}/api/v1/metrics?format=prometheus", timeout=30
        ) as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        families = parse_prometheus_text(text)  # raises on malformed text
        if "text/plain" not in ctype:
            problems.append(f"prometheus content-type {ctype!r}")
        for needed in (
            "kss_passes_total",
            "kss_pass_latency_seconds",
            "kss_uptime_seconds",
            # the fleet observatory gauges (utils/fleetstats.py) must
            # render AND survive the strict parse above
            "kss_fleet_pending_pods",
            "kss_fleet_utilization_mean",
            "kss_fleet_fragmentation_index",
            "kss_fleet_samples_total",
        ):
            if needed not in families:
                problems.append(f"metric family {needed} missing")
        if families.get("kss_passes_total", {}).get("samples", [(0, 0, 0)])[
            0
        ][2] < 1:
            problems.append("kss_passes_total did not count the pass")
        # the observatory's sample window must be non-empty after a pass
        with urllib.request.urlopen(
            f"{base}/api/v1/timeseries", timeout=30
        ) as r:
            ts = json.loads(r.read().decode())
        if not ts.get("enabled"):
            problems.append("/api/v1/timeseries reports stats disabled")
        if not ts.get("samples"):
            problems.append("/api/v1/timeseries window is empty after a pass")
        else:
            s = ts["samples"][-1]
            for field in ("devices", "buffers", "fleet"):
                if field not in s:
                    problems.append(f"timeseries sample missing {field!r}")
        # SSE: the stream must yield >= 1 event promptly
        req = urllib.request.Request(f"{base}/api/v1/events")
        sse_event = None
        with urllib.request.urlopen(req, timeout=30) as r:
            for _ in range(32):
                line = r.readline().decode()
                if line.startswith("event:"):
                    sse_event = line.split(":", 1)[1].strip()
                    break
        if sse_event is None:
            problems.append("SSE stream yielded no event")
        fields = {
            "prometheus_families": len(families),
            "sse_first_event": sse_event or "",
            "timeseries_samples": len(ts.get("samples") or ()),
        }
        return fields, problems
    finally:
        server.shutdown()
        fleetstats.deactivate()


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runnable from a bare checkout: the package lives at the repo root
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from kube_scheduler_simulator_tpu.utils.compilecache import (
        enable_compile_cache,
    )

    enable_compile_cache()
    trace_fields, problems = _trace_gate()
    server_fields, server_problems = _server_gates()
    problems += server_problems
    line = {"config": "observability_smoke", **trace_fields, **server_fields}
    print(json.dumps(line), flush=True)
    if problems:
        print(
            "observability-smoke FAILED: " + "; ".join(problems),
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
