"""Black-box placement-parity harness over the simulator REST API.

SURVEY.md §7 M4: the parity suite must be able to compare this framework
against the *reference simulator as a black box over its REST API* — not
only against the in-repo oracle (whose bugs can correlate with kernel
bugs; see the InterPodAffinity first-pod divergence found in round 1).

The harness drives any endpoint speaking the reference wire protocol
(`simulator/docs/api.md`): the Go reference (`make start` in the
reference repo, needs etcd + Go — not available in this build image) or
this framework's own server. Flow per backend:

  1. `PUT /api/v1/reset`
  2. `POST /api/v1/import` with the workload snapshot
  3. trigger scheduling — `POST /api/v1/schedule` when the endpoint has
     it (this framework's explicit-pass extension); the Go reference
     schedules continuously, so otherwise just wait
  4. poll pod state until every pod is bound or terminally pending
  5. extract placements (`spec.nodeName`) + the per-plugin result
     annotations

and the report diffs placements and (optionally) the 13 annotation
payloads between two backends.

Usage:
    python tools/parity_harness.py --a http://localhost:1212 \
        --b http://localhost:3131 --snapshot workload.json [--annotations]

Exit code 0 = parity, 1 = divergence (diff printed), 2 = driver error.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

SCHED_ANNOTATION_PREFIX = "scheduler-simulator/"


class Backend:
    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    def _req(self, method: str, path: str, payload=None):
        data = None if payload is None else json.dumps(payload).encode()
        req = urllib.request.Request(
            f"{self.base}{path}",
            data=data,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            body = resp.read()
            return json.loads(body) if body else None

    def reset(self):
        self._req("PUT", "/api/v1/reset")

    def import_snapshot(self, snapshot: dict):
        return self._req("POST", "/api/v1/import", snapshot)

    def try_trigger_schedule(self) -> bool:
        """Explicit scheduling pass where supported (this framework);
        the reference schedules continuously and 404s here."""
        try:
            self._req("POST", "/api/v1/schedule")
            return True
        except urllib.error.HTTPError as e:
            if e.code in (404, 405):
                return False
            raise

    def pods(self) -> list[dict]:
        # this framework's CRUD route first, then the reference's
        # kube-apiserver proxy shape
        for path in ("/api/v1/resources/pods",):
            try:
                out = self._req("GET", path)
                return out["items"] if isinstance(out, dict) else out
            except urllib.error.HTTPError as e:
                if e.code != 404:
                    raise
        out = self._req("GET", "/api/v1/export")
        return out.get("pods", [])

    def wait_for_placements(
        self,
        expected: int,
        settle_s: float = 2.0,
        timeout_s: float = 120.0,
        synchronous: bool = False,
    ) -> dict:
        """Poll until the bound-pod count is stable (the reference binds
        asynchronously). `synchronous=True` (the endpoint ran an explicit
        scheduling pass) means state is already final: zero binds settle
        after `settle_s`. Asynchronous backends get the full deadline
        before zero binds are read as all-unschedulable — a reference
        may take a long time to make its first bind. Returns
        {(ns/name): {"node": ..., "annotations": {scheduler only}}}."""
        deadline = time.monotonic() + timeout_s
        last_bound, last_change = -1, time.monotonic()
        while True:
            pods = self.pods()
            bound = sum(
                1 for p in pods if (p.get("spec") or {}).get("nodeName")
            )
            now = time.monotonic()
            if bound != last_bound:
                last_bound, last_change = bound, now
            if bound > 0 or synchronous:
                settle = settle_s
            else:
                settle = timeout_s  # only the deadline ends a zero-bind wait
            done = bound >= expected or now - last_change >= settle
            if done or now > deadline:
                return {
                    f"{(p['metadata'].get('namespace') or 'default')}/"
                    f"{p['metadata']['name']}": {
                        "node": (p.get("spec") or {}).get("nodeName", ""),
                        "annotations": {
                            k: v
                            for k, v in (
                                p["metadata"].get("annotations") or {}
                            ).items()
                            if k.startswith(SCHED_ANNOTATION_PREFIX)
                        },
                    }
                    for p in pods
                }
            time.sleep(0.25)


def run_backend(
    backend: Backend, snapshot: dict, settle_s: float = 120.0
) -> dict:
    backend.reset()
    backend.import_snapshot(snapshot)
    triggered = backend.try_trigger_schedule()
    return backend.wait_for_placements(
        expected=len(snapshot.get("pods", [])),
        synchronous=triggered,
        timeout_s=settle_s,
    )


def diff_results(a: dict, b: dict, annotations: bool = False) -> list[str]:
    lines = []
    for key in sorted(set(a) | set(b)):
        ra, rb = a.get(key), b.get(key)
        if ra is None or rb is None:
            lines.append(f"{key}: only in {'A' if rb is None else 'B'}")
            continue
        if ra["node"] != rb["node"]:
            lines.append(
                f"{key}: placement A={ra['node'] or '<none>'} "
                f"B={rb['node'] or '<none>'}"
            )
        elif annotations and ra["annotations"] != rb["annotations"]:
            keys = {
                k
                for k in set(ra["annotations"]) | set(rb["annotations"])
                if ra["annotations"].get(k) != rb["annotations"].get(k)
            }
            lines.append(f"{key}: annotation mismatch on {sorted(keys)}")
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--a", required=True, help="backend A base URL")
    ap.add_argument("--b", required=True, help="backend B base URL")
    ap.add_argument("--snapshot", required=True, help="workload JSON path")
    ap.add_argument(
        "--annotations",
        action="store_true",
        help="also compare the per-plugin result annotations",
    )
    ap.add_argument(
        "--timeout",
        type=float,
        default=30.0,
        help="per-HTTP-request timeout in seconds (the schedule-settle"
        " deadline is 4x this): raise it for slow backends — a cold jit"
        " compile or a loaded host can push one schedule request past"
        " the default",
    )
    args = ap.parse_args(argv)
    with open(args.snapshot) as f:
        snapshot = json.load(f)
    try:
        settle = max(120.0, 4 * args.timeout)
        res_a = run_backend(
            Backend(args.a, timeout=args.timeout), snapshot, settle_s=settle
        )
        res_b = run_backend(
            Backend(args.b, timeout=args.timeout), snapshot, settle_s=settle
        )
    except (urllib.error.URLError, OSError) as e:
        print(f"parity-harness: backend unreachable: {e}", file=sys.stderr)
        return 2
    lines = diff_results(res_a, res_b, annotations=args.annotations)
    if lines:
        print(f"DIVERGED ({len(lines)} differences):")
        for ln in lines:
            print("  " + ln)
        return 1
    print(
        f"PARITY: {len(res_a)} pods, "
        f"{sum(1 for r in res_a.values() if r['node'])} placed identically"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
