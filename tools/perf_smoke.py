"""`make perf-smoke`: tiny CPU-only lifecycle throughput sanity check.

Runs a small seeded churn timeline (Poisson arrivals + a cordon flap
against a 6-node cluster) through the full service stack — store events,
delta encoder, compiled engine — and asserts the wiring that makes churn
O(Δ) actually engaged:

  * the run Succeeds and schedules pods;
  * the delta encoder took over after warm-up (deltaEncodes > 0, and
    fullEncodes stays at the warm-up handful);
  * the phase-timing breakdown is populated (encode/execute seconds).

One JSON line on stdout (the bench.py contract); exit 0 on pass. Small
enough for tier-1 (seconds, CPU-only) — this is a sanity gate, not a
measurement; `python bench.py` owns the numbers.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # runnable from a bare checkout: the package lives at the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
    from kube_scheduler_simulator_tpu.utils.compilecache import (
        enable_compile_cache,
    )

    enable_compile_cache()
    nodes = [
        {
            "metadata": {"name": f"n{i}"},
            "status": {
                "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
            },
        }
        for i in range(6)
    ]
    # pre-bound seed pods hold the pod count inside ONE capacity bucket
    # for the whole run (first encode at 34 pods → bucket 64; 33 + 30
    # arrivals = 63 ≤ 64): the cold start is the only full encode
    seed_pods = [
        {
            "metadata": {"name": f"seed-{i}"},
            "spec": {
                "nodeName": f"n{i % 6}",
                "containers": [
                    {
                        "name": "c",
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "64Mi"}
                        },
                    }
                ],
            },
        }
        for i in range(33)
    ]
    spec = ChaosSpec.from_dict(
        {
            "name": "perf-smoke",
            "seed": 7,
            "horizon": 40.0,
            "schedulerMode": "gang",
            "snapshot": {"nodes": nodes, "pods": seed_pods},
            "arrivals": [
                {
                    "kind": "poisson",
                    "rate": 1.5,
                    "count": 30,
                    "template": {
                        "metadata": {"name": "churn"},
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "resources": {
                                        "requests": {
                                            "cpu": "100m",
                                            "memory": "64Mi",
                                        }
                                    },
                                }
                            ]
                        },
                    },
                }
            ],
            "faults": [
                {"at": 10.0, "action": "cordon", "node": "n0"},
                {"at": 20.0, "action": "uncordon", "node": "n0"},
            ],
        }
    )
    eng = LifecycleEngine(spec)
    result = eng.run()
    snap = result["metrics"]
    phases = snap.get("phases", {})
    wall = result["wallSeconds"]
    line = {
        "config": "perf_smoke",
        "phase": result["phase"],
        "events": result["events"],
        "passes": result["passes"],
        "arrived": result["pods"]["arrived"],
        "events_per_s": round(result["events"] / wall, 1) if wall > 0 else 0.0,
        "delta_encodes": phases.get("deltaEncodes", 0),
        "full_encodes": phases.get("fullEncodes", 0),
        "engine_builds": phases.get("engineBuilds", 0),
        "encode_s": phases.get("encodeSeconds", 0.0),
        "execute_s": phases.get("executeSeconds", 0.0),
    }
    print(json.dumps(line), flush=True)
    problems = []
    if result["phase"] != "Succeeded":
        problems.append(f"run phase {result['phase']!r}")
    if result["pods"]["arrived"] < 10:
        problems.append("timeline produced too few arrivals")
    if not phases:
        problems.append("phase-timing breakdown missing from metrics")
    if phases.get("deltaEncodes", 0) == 0:
        problems.append("delta encoder never engaged")
    if phases.get("fullEncodes", 0) > 3:
        problems.append(
            f"too many full re-encodes ({phases.get('fullEncodes')}) for a "
            "stable churn timeline"
        )
    if problems:
        print("perf-smoke FAILED: " + "; ".join(problems), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
