"""`make perf-smoke`: tiny CPU-only lifecycle throughput sanity check.

Four gates, one JSON line:

1. **Churn is O(Δ)** — a small seeded churn timeline (Poisson arrivals +
   a cordon flap against a 6-node cluster) through the full service
   stack (async pipelined dispatch since the stall-free-serving PR):
   the run Succeeds, the delta encoder carries it after warm-up
   (deltaEncodes > 0, fullEncodes stays at the warm-up handful), and the
   phase-timing breakdown is populated.

2. **Bucket crossings are stall-free** — a cluster filled past the 80%
   watermark of its pod-capacity bucket, scheduled once (the cold
   compile), drained (the broker's background speculative compile for
   the next bucket completes), then grown across the bucket boundary and
   scheduled again: the crossing pass must record ZERO synchronous
   compiles on the request thread (`compileMisses` stays at the cold
   start's 1, the crossing served by the `speculativeCompiles == 1`
   warm engine). The gang half of the gate: the fused whole-pass
   program (`gang.fixpoint`) compiles ONCE per bucket — zero ledger
   rebuilds, zero engine builds, and exactly one device dispatch per
   pass across warm churn at a stable bucket.

3. **Packing is free** — the packed low-precision encoding plane
   (`KSS_DTYPE_POLICY=packed`, engine/packing.py) against the TPU32
   baseline on a label-rich affinity cluster: placements AND trace
   byte-identical, encoded-cluster device bytes reduced ≥ 2x, and zero
   extra ledger-counted device dispatches per warm pass (the unpack is
   fused into the scheduling program, never its own dispatch).

4. **The program ledger answers and diffs clean** — the whole run
   executes under `KSS_PROGRAM_LEDGER=1` (utils/ledger.py): the ledger
   must be populated (≥1 program carrying compile seconds, FLOPs, and
   a call count), `analysis ledger-diff` of the persisted ledger
   against itself must exit 0, and a doctored copy with an injected
   compile-seconds regression must exit 1 — the perf-regression gate
   gating itself (docs/observability.md).

Exit 0 on pass. Small enough for tier-1 (seconds, CPU-only) — this is a
sanity gate, not a measurement; `python bench.py` owns the numbers.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def _crossing_gate() -> "tuple[dict, list[str]]":
    """Gate 2: warm-up → watermark speculation → bucket crossing with
    zero request-thread compiles. Returns (JSON fields, problems)."""
    from kube_scheduler_simulator_tpu.models.store import ResourceStore
    from kube_scheduler_simulator_tpu.server.service import SchedulerService
    from kube_scheduler_simulator_tpu.utils.broker import CompileBroker
    from kube_scheduler_simulator_tpu.utils.metrics import SchedulingMetrics

    from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod

    def _fixpoint_builds() -> "dict[str, int]":
        """builds per `gang.fixpoint` fingerprint — the ledger is
        process-global (the lifecycle gate's engines share labels AND
        fingerprints with ours), so every assertion below is a DELTA
        over this gate's own lifetime."""
        return {
            p["fingerprint"]: p["builds"]
            for p in ledger_mod.LEDGER.snapshot()["programs"]
            if p["label"] == "gang.fixpoint"
        }

    builds_at_start = _fixpoint_builds()
    store = ResourceStore()
    for i in range(6):
        store.apply(
            "nodes",
            {
                "metadata": {"name": f"x{i}"},
                "status": {
                    "allocatable": {"cpu": "64", "memory": "128Gi", "pods": "110"}
                },
            },
        )

    def churn_pod(name: str) -> dict:
        return {
            "metadata": {"name": name},
            "spec": {
                "containers": [
                    {
                        "name": "c",
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "64Mi"}
                        },
                    }
                ]
            },
        }

    # 52 pods: bucket 64, occupancy 81% — past the speculation watermark
    for i in range(52):
        store.apply("pods", churn_pod(f"p{i}"))
    metrics = SchedulingMetrics()
    # speculation forced ON: the gate must hold regardless of ambient
    # KSS_NO_SPECULATIVE_COMPILE (profiling) settings
    svc = SchedulerService(
        store,
        metrics=metrics,
        broker=CompileBroker(metrics=metrics, speculative=True),
    )
    svc.schedule_gang(record=False)  # cold start: the ONE allowed miss
    drained = svc.broker.drain(timeout=600)
    # cross the 64-pod bucket: 72 pods re-encode at capacity 128
    for i in range(52, 72):
        store.apply("pods", churn_pod(f"p{i}"))
    placements, _, _ = svc.schedule_gang(record=False)
    phases = metrics.snapshot()["phases"]
    fields = {
        "crossing_compile_misses": phases["compileMisses"],
        "crossing_compile_hits": phases["compileHits"],
        "crossing_speculative_compiles": phases["speculativeCompiles"],
        "crossing_stall_seconds": phases["stallSeconds"],
    }
    problems = []
    if not drained:
        problems.append("speculative compile did not finish in its window")
    if phases["speculativeCompiles"] < 1:
        problems.append("watermark never armed a speculative compile")
    if phases["compileMisses"] > 1:
        problems.append(
            f"bucket crossing paid a synchronous request-thread compile "
            f"(compileMisses {phases['compileMisses']}, expected 1 = cold start)"
        )
    if phases["compileHits"] < 1:
        problems.append("crossing pass was not served by the warm engine")
    bound = sum(1 for v in placements.values() if v)
    if bound < 20:
        problems.append(f"crossing pass scheduled too little ({bound}/20)")

    # gate 2b (gang fusion): the fused whole-pass program
    # (`gang.fixpoint`, engine/gang.py) compiles ONCE per bucket over
    # this gate's whole lifetime (cold start + speculation + crossing)
    # and stays warm across churn at a stable bucket — zero rebuilds,
    # zero engine builds, exactly one device dispatch per warm pass
    # (the one-dispatch contract, docs/performance.md "gang fixpoint
    # on device").
    builds_after_crossing = _fixpoint_builds()
    overbuilt = {
        fp: b - builds_at_start.get(fp, 0)
        for fp, b in builds_after_crossing.items()
        if b - builds_at_start.get(fp, 0) > 1
    }
    fields["gang_fixpoint_builds_delta"] = sum(
        b - builds_at_start.get(fp, 0)
        for fp, b in builds_after_crossing.items()
    )
    if not builds_after_crossing:
        problems.append(
            "fused gang program (gang.fixpoint) never reached the ledger"
        )
    if overbuilt:
        problems.append(
            f"fused gang program compiled more than once per bucket "
            f"within one service: {overbuilt}"
        )

    def _fixpoint_calls() -> int:
        return sum(
            p["calls"]
            for p in ledger_mod.LEDGER.snapshot()["programs"]
            if p["label"] == "gang.fixpoint"
        )

    engine_builds_before = metrics.snapshot()["phases"]["engineBuilds"]
    calls_before = _fixpoint_calls()
    warm_passes = 3
    for i in range(warm_passes):
        store.apply("pods", churn_pod(f"warm-{i}"))  # 75 pods: bucket 128
        svc.schedule_gang(record=False)
    phases = metrics.snapshot()["phases"]
    rebuilds = {
        fp: b - builds_after_crossing.get(fp, 0)
        for fp, b in _fixpoint_builds().items()
        if b - builds_after_crossing.get(fp, 0) > 0
    }
    fields["gang_warm_engine_builds_delta"] = (
        phases["engineBuilds"] - engine_builds_before
    )
    fields["gang_warm_dispatches"] = _fixpoint_calls() - calls_before
    if rebuilds:
        problems.append(
            f"fused gang program recompiled across warm churn at a "
            f"stable bucket: {rebuilds}"
        )
    if phases["engineBuilds"] != engine_builds_before:
        problems.append(
            f"warm gang churn at a stable bucket rebuilt engines "
            f"({engine_builds_before} -> {phases['engineBuilds']})"
        )
    if fields["gang_warm_dispatches"] != warm_passes:
        problems.append(
            f"expected {warm_passes} fused dispatches for {warm_passes} "
            f"warm gang passes, got {fields['gang_warm_dispatches']}"
        )
    return fields, problems


def _packing_gate() -> "tuple[dict, list[str]]":
    """Gate 4: the packed low-precision encoding plane
    (KSS_DTYPE_POLICY=packed, engine/packing.py). Three contracts on a
    label-rich affinity cluster: PACKED placements and trace
    byte-identical to TPU32, encoded-cluster device bytes reduced
    >= 2x, and ZERO extra device dispatches per warm pass — the unpack
    is fused into the scheduling program, never a separate dispatch."""
    import jax
    import numpy as np

    from kube_scheduler_simulator_tpu.engine import (
        PACKED,
        TPU32,
        encode_cluster,
    )
    from kube_scheduler_simulator_tpu.engine.engine import (
        BatchedScheduler,
        supported_config,
    )
    from kube_scheduler_simulator_tpu.engine.packing import (
        encoded_device_bytes,
    )
    from kube_scheduler_simulator_tpu.synth import synthetic_affinity_cluster
    from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod

    problems: list[str] = []
    cfg = supported_config()

    # the >= 2x byte floor: host-side accounting only (no scheduling),
    # so the measuring shape can afford enough label vocabulary to be
    # representative — bench.py --encoding-probe owns the real numbers
    nodes, pods = synthetic_affinity_cluster(96, 768, seed=11)
    wide = encoded_device_bytes(
        encode_cluster(nodes, pods, cfg, policy=TPU32)
    )
    narrow = encoded_device_bytes(
        encode_cluster(nodes, pods, cfg, policy=PACKED)
    )
    ratio = wide["total"] / narrow["total"]
    if ratio < 2.0:
        problems.append(
            f"packed encoding saves only {ratio:.2f}x encoded device "
            "bytes (< 2x floor)"
        )

    # placement/trace/dispatch parity at a smaller shape (two sequential
    # compiles are this gate's cost; the contract is shape-independent)
    nodes, pods = synthetic_affinity_cluster(48, 160, seed=3)

    def _seq_calls() -> "dict[tuple, int]":
        # keyed (label, fingerprint): both policies' programs share the
        # "seq.run" label (a policy flip is a distinct compile, not a
        # distinct site), so a label-only view would hide one of them
        return {
            (p["label"], p["fingerprint"]): p["calls"]
            for p in ledger_mod.LEDGER.snapshot()["programs"]
            if p["label"].startswith("seq.")
        }

    def one(policy):
        enc = encode_cluster(nodes, pods, cfg, policy=policy)
        sc = BatchedScheduler(enc, record=True)
        sc.run()  # compile + warm
        before = _seq_calls()
        state, out = sc.run()
        dispatches = sum(
            calls - before.get(key, 0)
            for key, calls in _seq_calls().items()
        )
        trace = [np.asarray(x) for x in jax.tree_util.tree_leaves(out)]
        return np.asarray(state.assignment), trace, dispatches

    base_asg, base_trace, base_disp = one(TPU32)
    packed_asg, packed_trace, packed_disp = one(PACKED)
    placements_ok = np.array_equal(base_asg, packed_asg)
    trace_ok = len(base_trace) == len(packed_trace) and all(
        b.dtype == p.dtype and np.array_equal(b, p)
        for b, p in zip(base_trace, packed_trace)
    )
    if not placements_ok:
        problems.append("PACKED placements diverge from TPU32")
    if not trace_ok:
        problems.append("PACKED trace bytes diverge from TPU32")
    if packed_disp != base_disp:
        problems.append(
            f"packed warm pass dispatched {packed_disp} programs vs "
            f"TPU32's {base_disp} (the in-kernel unpack contract is "
            "zero extra)"
        )
    fields = {
        "packed_bytes_ratio": round(ratio, 2),
        "packed_placements_identical": bool(placements_ok and trace_ok),
        "packed_extra_dispatches": packed_disp - base_disp,
    }
    return fields, problems


def _ledger_gate() -> "tuple[dict, list[str]]":
    """Gate 3: the program ledger is populated and its regression diff
    both passes clean documents and catches an injected regression."""
    from kube_scheduler_simulator_tpu.analysis.__main__ import (
        main as analysis_main,
    )
    from kube_scheduler_simulator_tpu.utils import ledger as ledger_mod

    problems: list[str] = []
    snap = ledger_mod.LEDGER.snapshot()
    populated = [
        p
        for p in snap["programs"]
        if p["compileSeconds"]["total"] > 0
        and p["flops"] is not None
        and p["calls"] >= 1
    ]
    if not populated:
        problems.append(
            "program ledger empty (KSS_PROGRAM_LEDGER armed, but no "
            "program recorded compile seconds + FLOPs + calls)"
        )
    clean_rc = regressed_rc = -1
    if populated:
        tmp = tempfile.mkdtemp(prefix="kss-perf-smoke-ledger-")
        base_path = os.path.join(tmp, "kss-program-ledger.json")
        ledger_mod.LEDGER.persist(base_path)
        clean_rc = analysis_main(["ledger-diff", base_path, base_path])
        if clean_rc != 0:
            problems.append(
                f"ledger-diff of the ledger against itself exited {clean_rc}"
            )
        doc = ledger_mod.load_ledger(base_path)
        bad_path = os.path.join(tmp, "regressed.json")
        bad = json.loads(json.dumps(doc))
        bad["programs"][0]["compileSeconds"]["total"] += 50.0
        with open(bad_path, "w") as f:
            json.dump(bad, f)
        regressed_rc = analysis_main(["ledger-diff", base_path, bad_path])
        if regressed_rc != 1:
            problems.append(
                f"injected compile-seconds regression was not flagged "
                f"(ledger-diff exited {regressed_rc}, expected 1)"
            )
    fields = {
        "ledger_programs": len(snap["programs"]),
        "ledger_diff_clean_rc": clean_rc,
        "ledger_diff_regressed_rc": regressed_rc,
    }
    return fields, problems


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # gate 3 needs the ledger armed for every engine the smoke builds
    os.environ["KSS_PROGRAM_LEDGER"] = "1"
    # runnable from a bare checkout: the package lives at the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
    from kube_scheduler_simulator_tpu.utils.compilecache import (
        enable_compile_cache,
    )

    enable_compile_cache()
    nodes = [
        {
            "metadata": {"name": f"n{i}"},
            "status": {
                "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
            },
        }
        for i in range(6)
    ]
    # pre-bound seed pods hold the pod count inside ONE capacity bucket
    # for the whole run (first encode at 34 pods → bucket 64; 33 + 30
    # arrivals = 63 ≤ 64): the cold start is the only full encode
    seed_pods = [
        {
            "metadata": {"name": f"seed-{i}"},
            "spec": {
                "nodeName": f"n{i % 6}",
                "containers": [
                    {
                        "name": "c",
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "64Mi"}
                        },
                    }
                ],
            },
        }
        for i in range(33)
    ]
    spec = ChaosSpec.from_dict(
        {
            "name": "perf-smoke",
            "seed": 7,
            "horizon": 40.0,
            "schedulerMode": "gang",
            "pipeline": "async",
            "snapshot": {"nodes": nodes, "pods": seed_pods},
            "arrivals": [
                {
                    "kind": "poisson",
                    "rate": 1.5,
                    "count": 30,
                    "template": {
                        "metadata": {"name": "churn"},
                        "spec": {
                            "containers": [
                                {
                                    "name": "c",
                                    "resources": {
                                        "requests": {
                                            "cpu": "100m",
                                            "memory": "64Mi",
                                        }
                                    },
                                }
                            ]
                        },
                    },
                }
            ],
            "faults": [
                {"at": 10.0, "action": "cordon", "node": "n0"},
                {"at": 20.0, "action": "uncordon", "node": "n0"},
            ],
        }
    )
    eng = LifecycleEngine(spec)
    result = eng.run()
    snap = result["metrics"]
    phases = snap.get("phases", {})
    wall = result["wallSeconds"]
    # settle the lifecycle run's broker before the crossing gate opens:
    # its watermark speculation may still be compiling in the
    # background, and gate 2b's compile-once deltas must not count a
    # prior stage's build landing mid-gate
    eng.scheduler.broker.drain(timeout=600)
    crossing_fields, crossing_problems = _crossing_gate()
    packing_fields, packing_problems = _packing_gate()
    ledger_fields, ledger_problems = _ledger_gate()
    line = {
        "config": "perf_smoke",
        "phase": result["phase"],
        "events": result["events"],
        "passes": result["passes"],
        "arrived": result["pods"]["arrived"],
        "events_per_s": round(result["events"] / wall, 1) if wall > 0 else 0.0,
        "delta_encodes": phases.get("deltaEncodes", 0),
        "full_encodes": phases.get("fullEncodes", 0),
        "engine_builds": phases.get("engineBuilds", 0),
        "encode_s": phases.get("encodeSeconds", 0.0),
        "execute_s": phases.get("executeSeconds", 0.0),
        "pipeline": "async",
        **crossing_fields,
        **packing_fields,
        **ledger_fields,
    }
    print(json.dumps(line), flush=True)
    problems = (
        list(crossing_problems)
        + list(packing_problems)
        + list(ledger_problems)
    )
    if result["phase"] != "Succeeded":
        problems.append(f"run phase {result['phase']!r}")
    if result["pods"]["arrived"] < 10:
        problems.append("timeline produced too few arrivals")
    if not phases:
        problems.append("phase-timing breakdown missing from metrics")
    if phases.get("deltaEncodes", 0) == 0:
        problems.append("delta encoder never engaged")
    if phases.get("fullEncodes", 0) > 3:
        problems.append(
            f"too many full re-encodes ({phases.get('fullEncodes')}) for a "
            "stable churn timeline"
        )
    if problems:
        print("perf-smoke FAILED: " + "; ".join(problems), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
