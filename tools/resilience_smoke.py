"""`make resilience-smoke`: CPU-only run-supervision sanity gate.

Three gates, one JSON line (docs/resilience.md):

1. **The ladder completes the run** — a short seeded chaos timeline run
   under ``KSS_FAULT_INJECT=compile_fail:1.0`` (every compile attempt
   fails) must still Succeed via the eager fallback, with
   ``eagerFallbacks > 0`` and ``degradedPasses > 0``, and its replayable
   trace must be BYTE-IDENTICAL to the clean run's — degradation changes
   latency, never results.

2. **Kill/resume loses nothing** — the same timeline driven through the
   real CLI (`python -m kube_scheduler_simulator_tpu.lifecycle`): a run
   stopped mid-horizon (``--stop-after-events``, the deterministic
   SIGTERM stand-in) with ``--checkpoint-to``, then ``--resume``d in a
   second CLI invocation, must produce a ``--trace-out`` file
   byte-identical to the uninterrupted run's — zero lost events, zero
   duplicates.

3. **Interrupted prefix is exact** — the killed run's trace file is a
   byte prefix of the uninterrupted trace, truncatable at the
   checkpoint's advertised ``traceByteOffset``.

Exit 0 on pass. Small enough for tier-1 wiring (seconds, CPU-only);
this is a sanity gate, not a measurement.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import tempfile


def _chaos_dict() -> dict:
    nodes = [
        {
            "metadata": {"name": f"n{i}"},
            "status": {
                "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
            },
        }
        for i in range(6)
    ]
    pods = [
        {
            "metadata": {"name": f"seed-{i}"},
            "spec": {
                "nodeName": f"n{i % 6}",
                "containers": [
                    {
                        "name": "c",
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "128Mi"}
                        },
                    }
                ],
            },
        }
        for i in range(33)
    ]
    return {
        "name": "resilience-smoke",
        "seed": 11,
        "horizon": 30.0,
        "schedulerMode": "gang",
        "pipeline": "async",
        "snapshot": {"nodes": nodes, "pods": pods},
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 0.5,
                "count": 10,
                "template": {
                    "metadata": {"name": "churn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
        "faults": [
            {"at": 8.0, "action": "cordon", "node": "n0"},
            {"at": 14.0, "action": "fail", "node": "n1"},
            {"at": 20.0, "action": "recover", "node": "n1"},
            {"at": 26.0, "action": "uncordon", "node": "n0"},
        ],
    }


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # deterministic gates: no ambient supervision settings, no
    # speculative compiles competing with the measurement
    for var in ("KSS_FAULT_INJECT", "KSS_COMPILE_DEADLINE_S"):
        os.environ.pop(var, None)
    os.environ.setdefault("KSS_NO_SPECULATIVE_COMPILE", "1")
    # runnable from a bare checkout: the package lives at the repo root
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from kube_scheduler_simulator_tpu.lifecycle.__main__ import (
        main as lifecycle_cli,
    )
    from kube_scheduler_simulator_tpu.lifecycle.checkpoint import (
        load_checkpoint,
    )
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
    from kube_scheduler_simulator_tpu.utils.compilecache import (
        enable_compile_cache,
    )

    enable_compile_cache()
    problems: list[str] = []

    # -- gate 1: persistent compile failure still completes, eagerly ----
    clean = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
    clean_res = clean.run()
    clean_trace = clean.trace_jsonl()
    if clean_res["phase"] != "Succeeded":
        problems.append(f"clean run phase {clean_res['phase']!r}")

    os.environ["KSS_FAULT_INJECT"] = "compile_fail:1.0"
    os.environ["KSS_COMPILE_BACKOFF_S"] = "0.01"
    try:
        faulted = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
        faulted_res = faulted.run()
    finally:
        os.environ.pop("KSS_FAULT_INJECT", None)
        os.environ.pop("KSS_COMPILE_BACKOFF_S", None)
    phases = faulted_res["metrics"]["phases"]
    if faulted_res["phase"] != "Succeeded":
        problems.append(
            f"faulted run phase {faulted_res['phase']!r} "
            f"({faulted_res.get('message', '')})"
        )
    if phases.get("eagerFallbacks", 0) < 1:
        problems.append("eager fallback never engaged under compile_fail:1.0")
    if phases.get("degradedPasses", 0) < 1:
        problems.append("no pass reported degraded under compile_fail:1.0")
    if faulted.trace_jsonl() != clean_trace:
        problems.append("degraded run's trace differs from the clean run's")

    # -- gates 2+3: CLI kill → checkpoint → resume, byte parity ---------
    tmp = tempfile.mkdtemp(prefix="kss-resilience-")
    spec_path = os.path.join(tmp, "spec.json")
    ckpt = os.path.join(tmp, "run.ckpt.json")
    killed_trace = os.path.join(tmp, "killed.jsonl")
    resumed_trace = os.path.join(tmp, "resumed.jsonl")
    with open(spec_path, "w") as f:
        json.dump(_chaos_dict(), f)
    # the CLI prints its result document; keep this tool's stdout to the
    # one-JSON-line contract by capturing the inner runs' output
    with contextlib.redirect_stdout(io.StringIO()):
        rc_kill = lifecycle_cli(
            [
                "--spec", spec_path,
                "--checkpoint-to", ckpt,
                "--stop-after-events", "7",
                "--trace-out", killed_trace,
            ]
        )
    if rc_kill != 0:
        # an Interrupted run WITH its final checkpoint is the orderly
        # drain: zero loss, so the CLI reports success (exit 0) to
        # rolling-restart supervisors (docs/resilience.md)
        problems.append(
            f"interrupted+checkpointed run exited {rc_kill} (the orderly "
            f"drain contract is exit 0)"
        )
    with contextlib.redirect_stdout(io.StringIO()):
        rc_resume = lifecycle_cli(
            ["--resume", ckpt, "--trace-out", resumed_trace]
        )
    if rc_resume != 0:
        problems.append(f"resumed run exited {rc_resume}")
    with open(killed_trace, "rb") as f:
        killed_bytes = f.read()
    with open(resumed_trace, "rb") as f:
        resumed_bytes = f.read()
    clean_bytes = clean_trace.encode()
    if resumed_bytes != clean_bytes:
        problems.append("resumed trace is not byte-identical to uninterrupted")
    if not clean_bytes.startswith(killed_bytes):
        problems.append("killed run's trace is not a prefix of uninterrupted")
    doc = load_checkpoint(ckpt)
    if doc["traceByteOffset"] != len(killed_bytes):
        problems.append(
            f"checkpoint traceByteOffset {doc['traceByteOffset']} != killed "
            f"trace length {len(killed_bytes)}"
        )
    lost = clean_trace.count("\n") - resumed_bytes.decode().count("\n")

    line = {
        "config": "resilience_smoke",
        "clean_phase": clean_res["phase"],
        "faulted_phase": faulted_res["phase"],
        "eager_fallbacks": phases.get("eagerFallbacks", 0),
        "degraded_passes": phases.get("degradedPasses", 0),
        "compile_retries": phases.get("compileRetries", 0),
        "trace_events": clean_res["events"],
        "killed_at_events": 7,
        "lost_events": lost,
        "trace_parity": resumed_bytes == clean_bytes,
    }
    print(json.dumps(line), flush=True)
    if lost != 0:
        problems.append(f"{lost} trace events lost across kill/resume")
    if problems:
        print(
            "resilience-smoke FAILED: " + "; ".join(problems), file=sys.stderr
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
