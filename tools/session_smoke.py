"""`make session-smoke`: the multi-tenant session plane end-to-end on CPU
(docs/sessions.md). Four gates, one JSON line:

1. **Shared warm engines** — 3 sessions with bucket-compatible clusters
   each run a scheduling pass; the SHARED CompileBroker's
   `compileMisses` must stay at the single-session cold-start count (1
   unique shape → 1 compile), every later tenant served warm.
2. **Evict/restore is lossless** — one session is evicted to its disk
   snapshot and touched back to life: the resource set (names AND
   resourceVersions) is byte-identical and the cumulative pass counters
   survive — eviction is load shedding, never data loss.
3. **Session admission** — creating sessions past KSS_MAX_SESSIONS
   sheds with the structured 503 (`error`/`kind`/`detail`) + Retry-After.
4. **Pod-quota admission** — pending pods past
   KSS_MAX_PENDING_PODS_PER_SESSION shed the same way.

Exit 0 on pass. Small enough for CI (seconds, CPU-only): a sanity gate,
not a benchmark.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.error
import urllib.request

N_SESSIONS = 3
MAX_SESSIONS = 1 + N_SESSIONS  # the implicit default + the tenants
POD_QUOTA = 4


def _req(port, method, path, body=None, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read()
            return resp.status, json.loads(raw) if raw else None, dict(resp.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, json.loads(raw) if raw else None, dict(e.headers)


def _node(name: str) -> dict:
    return {
        "metadata": {"name": name},
        "status": {
            "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
        },
    }


def _pod(name: str) -> dict:
    return {
        "metadata": {"name": name},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "resources": {
                        "requests": {"cpu": "100m", "memory": "64Mi"}
                    },
                }
            ]
        },
    }


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # deterministic broker counters: no background speculative builds
    os.environ["KSS_NO_SPECULATIVE_COMPILE"] = "1"
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from kube_scheduler_simulator_tpu.server import (
        SimulatorServer,
        SimulatorService,
    )
    from kube_scheduler_simulator_tpu.utils.compilecache import (
        enable_compile_cache,
    )

    enable_compile_cache()
    problems: list[str] = []
    server = SimulatorServer(
        SimulatorService(),
        port=0,
        session_config={
            "max_sessions": MAX_SESSIONS,
            "pending_pod_quota": POD_QUOTA,
        },
    ).start()
    try:
        p = server.port

        # -- gate 1: N bucket-compatible tenants, ONE compile ------------
        sids = []
        for i in range(N_SESSIONS):
            code, doc, _ = _req(
                p, "POST", "/api/v1/sessions", {"name": f"tenant-{i}"}
            )
            if code != 201:
                problems.append(f"session create {i} returned {code}")
                continue
            sids.append(doc["id"])
        for sid in sids:
            for i in range(4):
                _req(
                    p,
                    "PUT",
                    f"/api/v1/sessions/{sid}/resources/nodes",
                    _node(f"n{i}"),
                )
            for i in range(2):
                _req(
                    p,
                    "PUT",
                    f"/api/v1/sessions/{sid}/resources/pods",
                    _pod(f"w{i}"),
                )
            code, out, _ = _req(p, "POST", f"/api/v1/sessions/{sid}/schedule")
            if code != 200 or out["scheduled"] != 2:
                problems.append(f"session {sid}: schedule returned {code} {out}")
        code, lst, _ = _req(p, "GET", "/api/v1/sessions")
        broker = lst["broker"]
        if broker["compileMisses"] != 1:
            problems.append(
                f"expected the cold start's 1 compileMiss across "
                f"{N_SESSIONS} bucket-compatible sessions, got "
                f"{broker['compileMisses']}"
            )
        if broker["compileHits"] < N_SESSIONS - 1:
            problems.append(
                f"warm sharing missing: compileHits={broker['compileHits']}"
            )

        # -- gate 2: evict → restore with zero loss ----------------------
        victim = sids[0]
        code, before, _ = _req(
            p, "GET", f"/api/v1/sessions/{victim}/resources/pods"
        )
        code, mbefore, _ = _req(p, "GET", f"/api/v1/sessions/{victim}/metrics")
        code, ev, _ = _req(p, "POST", f"/api/v1/sessions/{victim}/evict")
        if code != 200:
            problems.append(f"evict returned {code}")
        code, info, _ = _req(p, "GET", f"/api/v1/sessions/{victim}")
        if info["state"] != "evicted":
            problems.append(f"victim state {info['state']!r} after evict")
        code, after, _ = _req(
            p, "GET", f"/api/v1/sessions/{victim}/resources/pods"
        )
        if code != 200 or after != before:
            problems.append("restored resources differ from pre-eviction")
        code, mafter, _ = _req(p, "GET", f"/api/v1/sessions/{victim}/metrics")
        if mafter["passes"] != mbefore["passes"]:
            problems.append(
                f"pass counters lost across evict/restore "
                f"({mbefore['passes']} -> {mafter['passes']})"
            )

        # -- gate 3: session admission past the limit --------------------
        code, err, headers = _req(p, "POST", "/api/v1/sessions", {})
        if code != 503:
            problems.append(f"over-limit session create returned {code}")
        else:
            if err.get("kind") != "SessionLimitExceeded" or "error" not in err:
                problems.append(f"unstructured admission 503: {err}")
            if not headers.get("Retry-After"):
                problems.append("admission 503 missing Retry-After")

        # -- gate 4: pending-pod quota ------------------------------------
        tenant = sids[1]
        base = f"/api/v1/sessions/{tenant}/resources/pods"
        for i in range(POD_QUOTA):  # fills up to the quota (2 are bound)
            _req(p, "PUT", base, _pod(f"q{i}"))
        code, err, headers = _req(p, "PUT", base, _pod("overflow"))
        if code != 503:
            problems.append(f"over-quota pod create returned {code}")
        elif err.get("kind") != "SessionQuotaExceeded" or not headers.get(
            "Retry-After"
        ):
            problems.append(f"unstructured quota 503: {err}")

        line = {
            "config": "session_smoke",
            "sessions": len(sids) + 1,
            "compile_misses": broker["compileMisses"],
            "compile_hits": broker["compileHits"],
            "evictions": lst["limits"]["evictions"] + 1,
            "restored_pods": len((after or {}).get("items", [])),
            "ok": not problems,
        }
        if problems:
            line["problems"] = problems
        print(json.dumps(line))
        return 0 if not problems else 1
    finally:
        server.shutdown()


if __name__ == "__main__":
    raise SystemExit(main())
