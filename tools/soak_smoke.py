"""`make soak-smoke`: the survivable-execution-plane chaos soak.

A randomized (but SEEDED — every schedule derives from ``--seed``)
interleaving of the three disturbance families the execution plane must
survive (docs/resilience.md), each asserted against the one invariant
that matters: the replayable trace stays BYTE-IDENTICAL to an
undisturbed run's, and nothing exits dirty. The lock-order witness
(``KSS_LOCK_CHECK=1``) is armed for the whole soak, so any acquisition
inversion the disturbances provoke fails the run loudly.

Stages:

1. **Clean reference** — the seeded chaos timeline, undisturbed; its
   trace is the byte oracle for every later stage.
2. **Device-fault ladder** — ``device_lost:1.0`` injected at the
   dispatch point: the run must complete on a LOWER rung
   (``deviceFailovers >= 1``, mesh shrink included when >1 device) with
   the oracle trace, never an Abort.
3. **Wedged dispatch** — ``dispatch_hang`` + a tiny
   ``KSS_DISPATCH_DEADLINE_S``: the watchdog must trip, the ladder must
   escalate, the trace must not change.
4. **Randomized kill/resume chain** — the CLI run is cut at
   seeded-random event counts (``--stop-after-events``, the
   deterministic SIGTERM stand-in), each segment exiting 0 (the orderly
   drain contract: Interrupted + final checkpoint = zero loss), each
   partial trace a byte prefix of the oracle, and the final resumed
   trace byte-identical.
5. **Real SIGTERM** — a subprocess CLI run killed with an actual
   ``kill -TERM`` mid-run must drain (exit 0) and resume to the oracle
   trace.
6. **Server drain** — an HTTP server with live sessions drains via
   ``POST /api/v1/admin/drain``: readyz flips to the distinct
   ``draining`` 503, new work sheds with the structured 503, every
   session (default included) snapshots, and a NEW manager over the
   same directory restores them transparently.

Exit 0 on pass; one JSON line on stdout. Seconds-to-minutes on CPU —
wired as ``make soak-smoke``, deliberately NOT tier-1.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

# armed BEFORE the package imports so every lock the soak touches is
# witness-wrapped (utils/locking.py decides at lock creation). The
# guarded-state witness (KSS_RACE_CHECK, docs/static-analysis.md
# KSS6xx) rides along: every inferred lock-claimed attribute is
# descriptor-checked for the whole soak — an unguarded access raises
# UnguardedAccess into a stage's problems instead of racing silently
os.environ["KSS_LOCK_CHECK"] = "1"
os.environ["KSS_RACE_CHECK"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("KSS_NO_SPECULATIVE_COMPILE", "1")
for _var in ("KSS_FAULT_INJECT", "KSS_DISPATCH_DEADLINE_S",
             "KSS_DISPATCH_RETRIES", "KSS_COMPILE_DEADLINE_S"):
    os.environ.pop(_var, None)

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _chaos_dict() -> dict:
    nodes = [
        {
            "metadata": {"name": f"n{i}"},
            "status": {
                "allocatable": {"cpu": "16", "memory": "32Gi", "pods": "110"}
            },
        }
        for i in range(6)
    ]
    pods = [
        {
            "metadata": {"name": f"seed-{i}"},
            "spec": {
                "nodeName": f"n{i % 6}",
                "containers": [
                    {
                        "name": "c",
                        "resources": {
                            "requests": {"cpu": "100m", "memory": "128Mi"}
                        },
                    }
                ],
            },
        }
        for i in range(33)
    ]
    return {
        "name": "soak-smoke",
        "seed": 23,
        "horizon": 30.0,
        "schedulerMode": "gang",
        "pipeline": "async",
        "snapshot": {"nodes": nodes, "pods": pods},
        "arrivals": [
            {
                "kind": "poisson",
                "rate": 0.5,
                "count": 12,
                "template": {
                    "metadata": {"name": "churn"},
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "resources": {
                                    "requests": {
                                        "cpu": "100m",
                                        "memory": "64Mi",
                                    }
                                },
                            }
                        ]
                    },
                },
            }
        ],
        "faults": [
            {"at": 7.0, "action": "cordon", "node": "n0"},
            {"at": 12.0, "action": "fail", "node": "n1"},
            {"at": 18.0, "action": "recover", "node": "n1"},
            {"at": 24.0, "action": "uncordon", "node": "n0"},
        ],
    }


def _http(method: str, url: str, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def main() -> int:
    import argparse
    import contextlib
    import io

    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    rng = random.Random(f"kss-soak:{args.seed}")

    from kube_scheduler_simulator_tpu.lifecycle.__main__ import (
        main as lifecycle_cli,
    )
    from kube_scheduler_simulator_tpu.lifecycle.engine import LifecycleEngine
    from kube_scheduler_simulator_tpu.scenario.chaos import ChaosSpec
    from kube_scheduler_simulator_tpu.utils.axonenv import scrubbed_cpu_env
    from kube_scheduler_simulator_tpu.utils.compilecache import (
        enable_compile_cache,
    )

    enable_compile_cache()
    problems: list[str] = []
    tmp = tempfile.mkdtemp(prefix="kss-soak-")
    spec_path = os.path.join(tmp, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(_chaos_dict(), f)

    def run_cli(argv: list[str]) -> int:
        with contextlib.redirect_stdout(io.StringIO()):
            return lifecycle_cli(argv)

    # -- stage 1: the undisturbed oracle --------------------------------
    clean = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
    clean_res = clean.run()
    clean_bytes = clean.trace_jsonl().encode()
    if clean_res["phase"] != "Succeeded":
        problems.append(f"clean run phase {clean_res['phase']!r}")
    total_events = clean_res["events"]

    # -- stage 2: device loss walks the ladder, answer unchanged --------
    os.environ["KSS_FAULT_INJECT"] = "device_lost:1.0"
    os.environ["KSS_DISPATCH_RETRIES"] = "1"
    try:
        lost = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
        lost_res = lost.run()
    finally:
        os.environ.pop("KSS_FAULT_INJECT", None)
        os.environ.pop("KSS_DISPATCH_RETRIES", None)
    lost_phases = lost_res["metrics"]["phases"]
    if lost_res["phase"] != "Succeeded":
        problems.append(
            f"device_lost run phase {lost_res['phase']!r} "
            f"({lost_res.get('message', '')})"
        )
    if lost_phases.get("deviceFailovers", 0) < 1:
        problems.append("device_lost:1.0 never reached the CPU rung")
    if lost.trace_jsonl().encode() != clean_bytes:
        problems.append("device_lost run's trace differs from the oracle")

    # -- stage 3: wedged dispatch trips the watchdog --------------------
    os.environ["KSS_FAULT_INJECT"] = "dispatch_hang:100ms"
    os.environ["KSS_DISPATCH_DEADLINE_S"] = "0.02"
    os.environ["KSS_DISPATCH_RETRIES"] = "1"
    try:
        hung = LifecycleEngine(ChaosSpec.from_dict(_chaos_dict()))
        hung_res = hung.run()
    finally:
        for var in ("KSS_FAULT_INJECT", "KSS_DISPATCH_DEADLINE_S",
                    "KSS_DISPATCH_RETRIES"):
            os.environ.pop(var, None)
    hung_phases = hung_res["metrics"]["phases"]
    if hung_res["phase"] != "Succeeded":
        problems.append(f"dispatch_hang run phase {hung_res['phase']!r}")
    if hung_phases.get("dispatchRetries", 0) < 1:
        problems.append("dispatch watchdog never tripped a retry")
    if hung.trace_jsonl().encode() != clean_bytes:
        problems.append("dispatch_hang run's trace differs from the oracle")

    # -- stage 4: seeded kill/resume chain ------------------------------
    ckpt = os.path.join(tmp, "chain.ckpt.json")
    cuts = sorted(rng.sample(range(2, max(3, total_events - 4)), 2))
    segments = 0
    for cut in cuts:
        seg_trace = os.path.join(tmp, f"chain-{segments}.jsonl")
        argv = ["--checkpoint-to", ckpt, "--stop-after-events", str(cut),
                "--trace-out", seg_trace]
        argv = (["--resume", ckpt] if segments else ["--spec", spec_path]) + argv
        rc = run_cli(argv)
        segments += 1
        if rc != 0:
            problems.append(f"chain segment {segments} (cut {cut}) exited {rc}")
        with open(seg_trace, "rb") as f:
            seg_bytes = f.read()
        if not clean_bytes.startswith(seg_bytes):
            problems.append(
                f"chain segment {segments}'s trace is not an oracle prefix"
            )
    final_trace = os.path.join(tmp, "chain-final.jsonl")
    rc = run_cli(["--resume", ckpt, "--trace-out", final_trace])
    if rc != 0:
        problems.append(f"chain final resume exited {rc}")
    with open(final_trace, "rb") as f:
        if f.read() != clean_bytes:
            problems.append("chain's final trace is not byte-identical")

    # -- stage 5: a REAL kill -TERM drains and resumes -------------------
    ckpt2 = os.path.join(tmp, "term.ckpt.json")
    killed_trace = os.path.join(tmp, "term-killed.jsonl")
    env = scrubbed_cpu_env()
    env["KSS_LOCK_CHECK"] = "1"
    env["KSS_RACE_CHECK"] = "1"
    env["KSS_NO_SPECULATIVE_COMPILE"] = "1"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kube_scheduler_simulator_tpu.lifecycle",
            "--spec", spec_path, "--checkpoint-to", ckpt2,
            "--checkpoint-every-events", "2", "--trace-out", killed_trace,
        ],
        env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    # the first periodic checkpoint proves the run is past its imports
    # and the graceful handlers are installed — only then pull the plug
    deadline = time.monotonic() + 300
    while (
        not os.path.exists(ckpt2)
        and proc.poll() is None
        and time.monotonic() < deadline
    ):
        time.sleep(0.2)
    if proc.poll() is None:
        time.sleep(rng.uniform(0.0, 1.0))  # land the signal mid-timeline
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=300)
    if proc.returncode != 0:
        problems.append(
            f"SIGTERM'd run exited {proc.returncode} "
            f"(stderr tail: {err[-300:].decode(errors='replace')!r})"
        )
    try:
        phase = json.loads(out.decode() or "{}").get("phase")
    except json.JSONDecodeError:
        phase = None
        problems.append("SIGTERM'd run printed no result document")
    if phase == "Succeeded":
        with open(killed_trace, "rb") as f:
            if f.read() != clean_bytes:
                problems.append("un-killed subprocess trace differs")
    else:
        if phase != "Interrupted":
            problems.append(f"SIGTERM'd run phase {phase!r}")
        term_trace = os.path.join(tmp, "term-final.jsonl")
        rc = run_cli(["--resume", ckpt2, "--trace-out", term_trace])
        if rc != 0:
            problems.append(f"post-SIGTERM resume exited {rc}")
        with open(term_trace, "rb") as f:
            if f.read() != clean_bytes:
                problems.append("post-SIGTERM resumed trace differs")

    # -- stage 6: HTTP server drain → restart → transparent restore -----
    from kube_scheduler_simulator_tpu.server.httpserver import SimulatorServer
    from kube_scheduler_simulator_tpu.server.sessions import SessionManager
    from kube_scheduler_simulator_tpu.server.service import SimulatorService

    snap_dir = os.path.join(tmp, "sessions")
    server = SimulatorServer(
        port=0, session_config={"snapshot_dir": snap_dir, "idle_evict_s": 0.0}
    ).start()
    base = f"http://127.0.0.1:{server.port}/api/v1"
    try:
        _, sess = _http("POST", f"{base}/sessions", {"name": "soak"})
        sid = sess["id"]
        _http("PUT", f"{base}/resources/nodes", {
            "metadata": {"name": "srv-n0"},
            "status": {"allocatable": {"cpu": "4", "memory": "8Gi",
                                       "pods": "110"}},
        })
        _http("PUT", f"{base}/sessions/{sid}/resources/pods", {
            "metadata": {"name": "srv-p0"},
            "spec": {"containers": [{"name": "c", "resources": {
                "requests": {"cpu": "100m", "memory": "64Mi"}}}]},
        })
        code, _ = _http("POST", f"{base}/admin/drain")
        if code != 202:
            problems.append(f"admin/drain answered {code}")
        deadline = time.monotonic() + 60
        status: dict = {}
        while time.monotonic() < deadline:
            _, status = _http("GET", f"{base}/admin/drain")
            if status.get("done"):
                break
            time.sleep(0.1)
        if not status.get("done"):
            problems.append("drain never completed")
        code, ready = _http("GET", f"{base}/readyz")
        if code != 503 or ready.get("state") != "draining":
            problems.append(
                f"draining readyz was {code}/{ready.get('state')!r}"
            )
        code, shed = _http("POST", f"{base}/schedule")
        if code != 503 or shed.get("kind") != "ServerDraining":
            problems.append(
                f"draining server answered {code}/{shed.get('kind')!r} "
                f"instead of shedding"
            )
        drained = (status.get("result") or {}).get("drainedSessions") or []
        if "default" not in drained or sid not in drained:
            problems.append(f"drain snapshotted {drained}, expected both")
    finally:
        server.shutdown()
    # "restart": a fresh manager over the same directory adopts the
    # snapshots — the default session's store restores in place
    mgr2 = SessionManager(SimulatorService(), snapshot_dir=snap_dir)
    if mgr2._sessions[  # noqa: SLF001 — white-box by design in the soak
        "default"
    ].service.store.count("nodes") != 1:
        problems.append("restarted default session lost the node")
    restored = mgr2.get(sid)
    if restored.service.store.count("pods") != 1:
        problems.append("restored session lost the pod")
    mgr2.shutdown()

    line = {
        "config": "soak_smoke",
        "seed": args.seed,
        "oracle_events": total_events,
        "device_failovers": lost_phases.get("deviceFailovers", 0),
        "mesh_shrinks": lost_phases.get("meshShrinks", 0),
        "dispatch_retries_hang": hung_phases.get("dispatchRetries", 0),
        "chain_cuts": cuts,
        "sigterm_phase": phase,
        "problems": len(problems),
    }
    print(json.dumps(line), flush=True)
    if problems:
        print("soak-smoke FAILED: " + "; ".join(problems), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
